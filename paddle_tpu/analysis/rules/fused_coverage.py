"""fused-coverage: which zoo families ride the fused decode tail.

The fused decode-tail megakernels (ops/pallas/decode_tail) only engage
when ``fused_decode_structural`` accepts a family's decoder layer — a
quiet structural change (a new bias, a qk-norm, a non-RMSNorm) silently
drops the family back to the discrete kernels and shows up as a perf
regression weeks later, if ever. This rule sweeps the tiny-config zoo
through the STRUCTURAL half of the gate on every default pdlint run and
pins the passing set both ways:

- a family in ``FUSED_FLOOR`` that stops passing is a coverage
  REGRESSION (the finding names the family);
- a family passing that is NOT in the floor must be added to it (the
  pin stays exact, like the catalog lints' two-direction checks).

Whisper (enc-dec) and gpt2 (non-llama attention) are not candidates —
the fused tail is a llama-family decode optimization by construction.
"""
from __future__ import annotations

import os
from typing import Iterable, List

from ..core import Finding, ProjectRule, register_rule

__all__ = ["FUSED_FLOOR", "CANDIDATES", "structural_coverage"]

#: zoo families with a llama-style decode path — what the sweep builds
CANDIDATES = ("llama", "mixtral", "qwen2", "qwen3", "mistral", "gemma",
              "gemma2", "phi3", "olmo2", "glm", "qwen2-moe",
              "deepseek-mla")

#: the pinned floor: families whose decoder layers pass the structural
#: fused-decode gate today. qwen2/glm carry qkv bias, qwen3/olmo2
#: qk-norm, gemma2 extra post-norms, deepseek-mla MLA attention — all
#: correctly off the fused path.
FUSED_FLOOR = frozenset({"llama", "mixtral", "mistral", "gemma", "phi3"})

_ANCHOR = "paddle_tpu/models/llama.py"


def _decoder_layer(model):
    for sub in model.sublayers():
        if getattr(sub, "self_attn", None) is not None:
            return sub
    return None


# the sweep builds a dozen tiny models (~seconds); every pdlint family
# gate in a test process runs this rule, so memoize per candidate set
_COVERAGE_CACHE: dict = {}


def structural_coverage(candidates=CANDIDATES) -> dict:
    """{family: passes structural gate} over tiny-config zoo builds."""
    hit = _COVERAGE_CACHE.get(candidates)
    if hit is not None:
        return dict(hit)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp

    from ...models.llama import fused_decode_structural
    from ..graph import zoo

    out = {}
    for name in candidates:
        layer = _decoder_layer(zoo.entry(name, full=True).build())
        out[name] = (layer is not None
                     and fused_decode_structural(layer, jnp.bfloat16))
    _COVERAGE_CACHE[candidates] = dict(out)
    return out


@register_rule
class FusedCoverageRule(ProjectRule):
    id = "fused-coverage"
    rationale = ("a structural change silently dropping a family off "
                 "the fused decode tail is a perf regression nobody "
                 "sees — the floor pins which families pass the gate, "
                 "both directions")

    def check_project(self, root: str) -> Iterable[Finding]:
        coverage = structural_coverage()
        out: List[Finding] = []
        for name in sorted(FUSED_FLOOR):
            if not coverage.get(name, False):
                out.append(Finding(
                    file=_ANCHOR, line=1, rule=self.id,
                    symbol="fused-coverage",
                    message=(f"fused-decode coverage regression: family "
                             f"'{name}' no longer passes "
                             "fused_decode_structural — its serving "
                             "decode fell back to the discrete kernels "
                             "(remove it from FUSED_FLOOR only if the "
                             "structural change is deliberate)")))
        for name, ok in sorted(coverage.items()):
            if ok and name not in FUSED_FLOOR:
                out.append(Finding(
                    file=_ANCHOR, line=1, rule=self.id,
                    symbol="fused-coverage",
                    message=(f"family '{name}' now passes the fused "
                             "decode structural gate but is not in "
                             "FUSED_FLOOR — add it so the coverage "
                             "gain is pinned")))
        return out
