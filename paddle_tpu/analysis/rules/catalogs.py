"""metrics-catalog / span-catalog / event-catalog / alert-catalog: docs
and registries agree, both ways.

The first two rules are the grown-up form of the original tier-1 lint
scripts (scripts/check_metrics_catalog.py, check_span_catalog.py),
re-homed under the pdlint runner; the scripts remain as thin wrappers.

- **metrics-catalog**: every metric family registered at import of
  ``paddle_tpu.observability`` has a row in docs/SERVING.md's "Metric
  catalog" table (name, kind, labels) and vice versa, with schema drift
  (kind/labels mismatch) flagged per row.
- **span-catalog**: every name in ``tracing.SPAN_CATALOG`` has a row in
  the "Span catalog" table and vice versa, and every registered span's
  ``SPAN_*`` constant is actually referenced outside tracing.py (no dead
  catalog entries).
- **event-catalog**: the flight recorder's ``EVENT_CATALOG`` kinds
  (flightrecorder.py) against the docs "Event catalog" table the same
  way — documented, registered, and every ``EV_*`` constant actually
  recorded outside flightrecorder.py.
- **alert-catalog**: the default SLO objectives
  (``alerts.DEFAULT_OBJECTIVES`` ∪ ``alerts.CLUSTER_OBJECTIVES``)
  against the docs "Alert catalog" table both ways, PLUS every metric
  an objective reads must actually exist (a registered family or a
  declared federated series) — an alert burning against a renamed
  counter would silently never fire.

The comparison cores are pure functions over parsed dicts so fixture
tests can exercise drift cases without importing the live registry.
"""
from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Set, Tuple

from ..core import Finding, ProjectRule, register_rule

_DOCS = os.path.join("docs", "SERVING.md")

# catalog rows look like: | `name` | kind | labels | meaning |
_METRIC_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|\s*([a-z]+)\s*\|\s*([^|]*)\|")
# span rows look like: | `serving.request` | parent | meaning |
_SPAN_ROW = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|")


# ---- pure comparison cores --------------------------------------------------

def compare_metric_catalogs(docs: Dict[str, tuple],
                            registry: Dict[str, tuple]
                            ) -> List[str]:
    problems = []
    for name in sorted(set(registry) - set(docs)):
        problems.append(f"metric registered but not in docs/SERVING.md: "
                        f"{name}")
    for name in sorted(set(docs) - set(registry)):
        problems.append(f"metric documented but not registered: {name}")
    for name in sorted(set(docs) & set(registry)):
        if docs[name] != registry[name]:
            problems.append(
                f"schema drift for {name}: docs say "
                f"{docs[name][0]}{sorted(docs[name][1])}, registry has "
                f"{registry[name][0]}{sorted(registry[name][1])}")
    return problems


def compare_span_catalogs(docs: Set[str], registered: Set[str],
                          emitted_ok: Dict[str, bool]) -> List[str]:
    return compare_name_catalogs(docs, registered, emitted_ok,
                                 noun="span", home="tracing.py")


def compare_event_catalogs(docs: Set[str], registered: Set[str],
                           emitted_ok: Dict[str, bool]) -> List[str]:
    return compare_name_catalogs(docs, registered, emitted_ok,
                                 noun="event", home="flightrecorder.py")


def compare_alert_catalogs(docs: Set[str], registered: Set[str],
                           metric_refs: Dict[str, List[str]],
                           known_metrics: Set[str]) -> List[str]:
    """Docs ↔ objective registries both ways (the shared name-catalog
    core), plus the alert-specific third leg: every metric an objective
    reads must exist — in the metrics registry or the declared
    federated-series set."""
    problems = compare_name_catalogs(docs, registered, {}, noun="alert",
                                     home="alerts.py")
    for name in sorted(metric_refs):
        for metric in metric_refs[name]:
            if metric not in known_metrics:
                problems.append(
                    f"alert {name!r} reads metric {metric!r}, which is "
                    "neither a registered metric family nor a declared "
                    "federated series — the objective can never fire")
    return problems


def compare_name_catalogs(docs: Set[str], registered: Set[str],
                          emitted_ok: Dict[str, bool], noun: str,
                          home: str) -> List[str]:
    """The shared docs/registry/emit three-way check behind the span and
    event catalog rules (they differ only in nouns and home module)."""
    problems = []
    for name in sorted(registered - docs):
        problems.append(f"{noun} registered but not in docs/SERVING.md: "
                        f"{name}")
    for name in sorted(docs - registered):
        problems.append(f"{noun} documented but not registered: {name}")
    for name, ok in sorted(emitted_ok.items()):
        if not ok:
            problems.append(
                f"{noun} {name!r} is registered but never emitted outside "
                f"{home}")
    return problems


# ---- docs parsing -----------------------------------------------------------

def documented_metrics(path: str) -> Dict[str, tuple]:
    """{name: (kind, frozenset(labels))} parsed from the docs table."""
    out = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            m = _METRIC_ROW.match(line.strip())
            if not m:
                continue
            name, kind, labels_cell = m.groups()
            if kind not in ("counter", "gauge", "histogram"):
                continue  # the stats()-mapping table, not the catalog
            labels = frozenset(
                l.strip() for l in labels_cell.split(",")
                if l.strip() and l.strip() != "—")
            out[name] = (kind, labels)
    return out


def documented_spans(path: str) -> Set[str]:
    """Span names from the docs "Span catalog" section only."""
    return _documented_names(path, "Span catalog", "span")


def documented_events(path: str) -> Set[str]:
    """Event kinds from the docs "Event catalog" section only."""
    return _documented_names(path, "Event catalog", "kind")


def documented_alerts(path: str) -> Set[str]:
    """Alert names from the docs "Alert catalog" section only."""
    return _documented_names(path, "Alert catalog", "alert")


def _documented_names(path: str, section: str, header_cell: str) -> Set[str]:
    out = set()
    in_section = False
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line.startswith("#"):
                in_section = line.lstrip("#").strip() == section
                continue
            if not in_section:
                continue
            m = _SPAN_ROW.match(line)
            if m and m.group(1) != header_cell:
                out.add(m.group(1))
    return out


def _bootstrap(root: str):
    import sys

    if root not in sys.path:
        sys.path.insert(0, root)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ---- rules ------------------------------------------------------------------

@register_rule
class MetricsCatalogRule(ProjectRule):
    id = "metrics-catalog"
    rationale = ("a metric must neither ship undocumented nor linger in "
                 "the docs after removal, and the documented schema must "
                 "match the registry")

    def check_project(self, root: str) -> Iterable[Finding]:
        _bootstrap(root)
        from paddle_tpu.observability import get_registry

        docs = documented_metrics(os.path.join(root, _DOCS))
        reg = {name: (d["kind"], frozenset(d["labels"]))
               for name, d in get_registry().describe().items()}
        for msg in compare_metric_catalogs(docs, reg):
            yield Finding(file=_DOCS.replace(os.sep, "/"), line=1,
                          rule=self.id, message=msg,
                          symbol="metric-catalog")


@register_rule
class SpanCatalogRule(ProjectRule):
    id = "span-catalog"
    rationale = ("a span must be documented, registered, and actually "
                 "emitted — dead catalog entries and undocumented spans "
                 "both drift")

    def check_project(self, root: str) -> Iterable[Finding]:
        _bootstrap(root)
        from paddle_tpu.observability import tracing

        docs = documented_spans(os.path.join(root, _DOCS))
        registered = set(tracing.SPAN_CATALOG)
        used = self._emitted_constants(root)
        emitted_ok = {
            value: (const in used)
            for const, value in vars(tracing).items()
            if (const.startswith("SPAN_") and isinstance(value, str)
                and const != "SPAN_CATALOG")
        }
        for msg in compare_span_catalogs(docs, registered, emitted_ok):
            yield Finding(file=_DOCS.replace(os.sep, "/"), line=1,
                          rule=self.id, message=msg, symbol="span-catalog")

    @staticmethod
    def _emitted_constants(root: str) -> Set[str]:
        """SPAN_* constants referenced OUTSIDE tracing.py (emit sites)."""
        return _referenced_constants(root, r"\bSPAN_[A-Z_]+\b",
                                     "tracing.py")


def _referenced_constants(root: str, pattern: str,
                          home_file: str) -> Set[str]:
    """Constants matching ``pattern`` referenced in paddle_tpu/ OUTSIDE
    the catalog's home module (i.e. real emit sites)."""
    used: Set[str] = set()
    pkg = os.path.join(root, "paddle_tpu")
    for dirpath, _, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py") or fn == home_file:
                continue
            with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                used.update(re.findall(pattern, f.read()))
    return used


@register_rule
class AlertCatalogRule(ProjectRule):
    id = "alert-catalog"
    rationale = ("a default SLO objective must be documented (operators "
                 "act on alert names), every documented alert must still "
                 "exist, and every metric an objective reads must be "
                 "real — an alert over a renamed counter silently never "
                 "fires")

    def check_project(self, root: str) -> Iterable[Finding]:
        _bootstrap(root)
        from paddle_tpu.observability import alerts, get_registry

        docs = documented_alerts(os.path.join(root, _DOCS))
        objectives = dict(alerts.DEFAULT_OBJECTIVES)
        objectives.update(alerts.CLUSTER_OBJECTIVES)
        metric_refs = {n: o.metric_names() for n, o in objectives.items()}
        known = set(get_registry().names()) | set(alerts.FEDERATED_SERIES)
        for msg in compare_alert_catalogs(docs, set(objectives),
                                          metric_refs, known):
            yield Finding(file=_DOCS.replace(os.sep, "/"), line=1,
                          rule=self.id, message=msg,
                          symbol="alert-catalog")


@register_rule
class EventCatalogRule(ProjectRule):
    id = "event-catalog"
    rationale = ("a flight-recorder event kind must be documented, "
                 "registered, and actually recorded — dead catalog "
                 "entries and undocumented kinds both drift, and an "
                 "undocumented kind makes incident bundles unreadable")

    def check_project(self, root: str) -> Iterable[Finding]:
        _bootstrap(root)
        from paddle_tpu.observability import flightrecorder

        docs = documented_events(os.path.join(root, _DOCS))
        registered = set(flightrecorder.EVENT_CATALOG)
        used = _referenced_constants(root, r"\bEV_[A-Z_]+\b",
                                     "flightrecorder.py")
        emitted_ok = {
            value: (const in used)
            for const, value in vars(flightrecorder).items()
            if (const.startswith("EV_") and isinstance(value, str)
                and const != "EVENT_CATALOG")
        }
        for msg in compare_event_catalogs(docs, registered, emitted_ok):
            yield Finding(file=_DOCS.replace(os.sep, "/"), line=1,
                          rule=self.id, message=msg,
                          symbol="event-catalog")
