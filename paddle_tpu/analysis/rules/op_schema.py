"""op-schema: the declarative op table stays internally consistent.

The reference's ops.yaml is validated by its generators at build time —
a bad dtype list or a duplicate op name fails the build, not a user.
Our ``OpDecl``/``Retrofit`` tables (paddle_tpu/ops/schema.py) are plain
Python, so nothing stops a typo'd category, a dtype jax doesn't know, a
differentiable op with no grad strategy, or two declarations silently
shadowing one name (``register_retrofits`` skips names already in OPS —
exactly the silent-drift case). This rule is the registration-time
validator, plus a cross-check against the OpTest sweep enumeration
(tests/test_op_suite.py): every declared op must be swept (OpSpec name
or ``covers``), whitelisted with a reason, or carry a ``tested_by``
pointer at a real test — statically, the same contract
``test_registry_swept`` enforces at runtime.

The validation core (``check_records``) is a pure function over the
declaration records so fixture tests can feed known-bad tables without
touching the real schema.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Callable, Iterable, List, Set, Tuple

from ..core import Finding, ProjectRule, register_rule

VALID_CATEGORIES = {
    "math", "linalg", "manipulation", "creation", "nn", "signal",
    "special", "random", "indexing", "fft",
}
VALID_DTYPES = {
    "float32", "float64", "bfloat16", "float16",
    "int8", "int16", "int32", "int64", "uint8", "bool",
    "complex64", "complex128",
}

_SCHEMA_FILE = "paddle_tpu/ops/schema.py"
_SWEEP_FILE = os.path.join("tests", "test_op_suite.py")
_SPEC_CTORS = {"OpSpec", "U", "B", "RED"}


def check_records(decls, retrofits,
                  enumerated: Set[str],
                  tested_by_ok: Callable[[str], bool]
                  ) -> List[Tuple[str, str]]:
    """Validate declaration records; returns (op-name, message) pairs.

    ``decls``: objects with name/category/dtypes/differentiable/vjp/
    n_outputs. ``retrofits``: objects with name/category/tested_by.
    ``enumerated``: op names the sweep covers (spec names + covers +
    whitelist). ``tested_by_ok(ref)``: does a tested_by pointer resolve.
    """
    problems: List[Tuple[str, str]] = []
    seen: Set[str] = set()
    for d in decls:
        if d.name in seen:
            problems.append((d.name, f"duplicate OpDecl name {d.name!r}"))
        seen.add(d.name)
        if d.category not in VALID_CATEGORIES:
            problems.append((d.name, f"op {d.name!r}: unknown category "
                             f"{d.category!r} (valid: "
                             f"{sorted(VALID_CATEGORIES)})"))
        bad = [t for t in d.dtypes if t not in VALID_DTYPES]
        if bad:
            problems.append((d.name,
                             f"op {d.name!r}: unknown dtypes {bad}"))
        if getattr(d, "n_outputs", 1) < 1:
            problems.append((d.name, f"op {d.name!r}: n_outputs must be "
                             ">= 1"))
        if d.differentiable and not str(getattr(d, "vjp", "")).strip():
            problems.append((d.name,
                             f"op {d.name!r} is differentiable but "
                             "declares no grad strategy (vjp)"))
    for r in retrofits:
        if r.name in seen:
            problems.append((r.name,
                             f"retrofit {r.name!r} shadows another "
                             "declaration (register_retrofits silently "
                             "skips names already registered)"))
        seen.add(r.name)
        if r.category not in VALID_CATEGORIES:
            problems.append((r.name, f"retrofit {r.name!r}: unknown "
                             f"category {r.category!r}"))
        if r.tested_by and not tested_by_ok(r.tested_by):
            problems.append((r.name,
                             f"retrofit {r.name!r}: tested_by "
                             f"{r.tested_by!r} does not point at an "
                             "existing test"))

    def covered(name: str, tested_by: str = "") -> bool:
        if name in enumerated or name.rstrip("_") in enumerated:
            return True
        return bool(tested_by) and tested_by_ok(tested_by)

    for d in decls:
        if not covered(d.name):
            problems.append((d.name,
                             f"op {d.name!r} is not enumerated by the "
                             "OpTest sweep (no OpSpec/covers/whitelist "
                             "entry in tests/test_op_suite.py)"))
    for r in retrofits:
        if not covered(r.name, r.tested_by):
            problems.append((r.name,
                             f"retrofit {r.name!r} is not enumerated by "
                             "the OpTest sweep and has no tested_by "
                             "pointer"))
    return problems


def sweep_enumeration(sweep_path: str) -> Set[str]:
    """Statically collect the op names tests/test_op_suite.py sweeps:
    OpSpec/U/B/RED names, their ``covers`` tuples, and WHITELIST keys."""
    with open(sweep_path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=sweep_path)
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in _SPEC_CTORS:
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                names.add(node.args[0].value.split(".")[-1])
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    names.add(str(kw.value.value).split(".")[-1])
                if kw.arg == "covers" and isinstance(kw.value,
                                                     (ast.Tuple, ast.List)):
                    names.update(e.value for e in kw.value.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, str))
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == "WHITELIST"
                   for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                names.update(k.value for k in node.value.keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str))
    return names


def make_tested_by_checker(root: str) -> Callable[[str], bool]:
    """``tests/test_x.py::test_y`` -> the file exists and defines the
    test function (textual — no test import at lint time)."""

    def ok(ref: str) -> bool:
        if "::" not in ref:
            return False
        rel, test = ref.split("::", 1)
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            return False
        with open(path, encoding="utf-8") as fh:
            return bool(re.search(
                rf"^def {re.escape(test)}\b", fh.read(), re.M))

    return ok


@register_rule
class OpSchemaRule(ProjectRule):
    id = "op-schema"
    rationale = ("an invalid OpDecl/Retrofit (bad dtype/category, "
                 "shadowed name, missing grad strategy, un-swept op) "
                 "ships silently — the generators the reference had at "
                 "build time")

    def check_project(self, root: str) -> Iterable[Finding]:
        import sys

        if root not in sys.path:
            sys.path.insert(0, root)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from paddle_tpu.ops import registry as _registry
        from paddle_tpu.ops import schema as _schema

        enumerated = sweep_enumeration(os.path.join(root, _SWEEP_FILE))
        tested_ok = make_tested_by_checker(root)
        problems = check_records(_schema.DECLS, _schema.RETROFITS,
                                 enumerated, tested_ok)
        # materialization check: every OpDecl must be live in the
        # registry with its declaration attached (the generated-dispatch
        # invariant — a decl that didn't materialize serves nothing)
        for d in _schema.DECLS:
            op = _registry.OPS.get(d.name)
            if op is None or op.decl is not d:
                problems.append((d.name,
                                 f"op {d.name!r} declared in DECLS but "
                                 "not materialized into ops.registry.OPS"))
        for name, msg in problems:
            yield Finding(file=_SCHEMA_FILE, line=1, rule=self.id,
                          message=msg, symbol=name)
