"""host-sync: no hidden device→host syncs on serving hot paths.

A TPU decode step is a single fused dispatch; the engine's throughput
model assumes exactly ONE device→host transfer per step (the sampled
tokens). Any extra ``.item()`` / ``int()`` / ``float()`` /
``np.asarray()`` on a device value inside ``step()`` or a
decode/prefill-path function blocks the host on the device queue and
serializes dispatch — the classic silent 10x in serving loops.

Scope: functions named ``step`` (or containing ``decode``/``prefill``/
``spec`` — the engine speculation path ``_step_speculative`` and the
speculative_generate/mtp round loops are decode hot paths too) in the
hot-path modules (serving.py, generation.py, speculative.py).
The rule does LOCAL taint tracking rather than banning ``np.asarray``
outright: a name assigned from a device-producing call (``jnp.*``, a
jitted step, any non-host call) is device-tainted; converting it — or a
subscript of it — to host is a finding, while host-side bookkeeping
(``np.asarray`` of a Python list, ``int()`` of a length) stays legal.
Deliberate sync points (the one per-step token fetch) carry an inline
``# pdlint: disable=host-sync`` pragma, which is the documentation.

Always flagged in hot functions, taint or not: ``.item()``,
``.block_until_ready()``, ``jax.device_get()``.
"""
from __future__ import annotations

import ast
import os
from typing import Iterable, Set

from ..core import Finding, ModuleContext, Rule, register_rule

HOT_MODULES = {"serving.py", "generation.py", "speculative.py"}
# "spec" pulls the engine speculation path (_step_speculative, the
# speculative round loops) into scope: a per-round host sync beyond the
# deliberate pragma'd fetch would serialize the multi-token dispatches
# exactly like it would the one-token loop
_HOT_NAME_PARTS = ("decode", "prefill", "spec")

# calls whose results stay host-side (taint sinks, not sources)
_HOST_BUILTINS = {
    "len", "int", "float", "bool", "str", "list", "tuple", "dict", "set",
    "sorted", "min", "max", "sum", "abs", "enumerate", "zip", "range",
    "getattr", "hasattr", "isinstance", "repr",
}
_HOST_PREFIXES = ("numpy.", "time.", "os.", "math.")
_SYNC_CONVERTERS = {"numpy.asarray", "numpy.array", "int", "float"}


def _is_hot_module(path: str) -> bool:
    return os.path.basename(path) in HOT_MODULES


def _is_hot_function(name: str) -> bool:
    return name == "step" or any(p in name for p in _HOT_NAME_PARTS)


@register_rule
class HostSyncRule(Rule):
    id = "host-sync"
    rationale = ("device→host syncs inside step()/decode/prefill paths "
                 "block dispatch and serialize the serving loop")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not _is_hot_module(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and _is_hot_function(node.name)):
                yield from self._check_hot(ctx, node)

    def _check_hot(self, ctx: ModuleContext, fn) -> Iterable[Finding]:
        tainted: Set[str] = set()
        host: Set[str] = set()
        # statement-ordered walk so assignments taint before uses
        for node in self._ordered(fn):
            if isinstance(node, ast.Assign):
                self._track(ctx, node.value, node.targets, tainted, host)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None:
                    self._track(ctx, node.value, [node.target], tainted,
                                host)
            if not isinstance(node, ast.Call):
                continue
            path = ctx.resolve_call(node.func)
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr == "item" and not node.args:
                    yield self.finding(
                        ctx, node.lineno,
                        f"`.item()` in hot-path function '{fn.name}' "
                        "forces a device→host sync per call")
                    continue
                if attr == "block_until_ready":
                    yield self.finding(
                        ctx, node.lineno,
                        f"`.block_until_ready()` in hot-path function "
                        f"'{fn.name}' blocks the dispatch queue")
                    continue
            if path == "jax.device_get":
                yield self.finding(
                    ctx, node.lineno,
                    f"`jax.device_get` in hot-path function '{fn.name}' "
                    "forces a device→host sync")
                continue
            if path in _SYNC_CONVERTERS and node.args:
                arg = node.args[0]
                base = None
                if isinstance(arg, ast.Name):
                    base = arg.id
                elif (isinstance(arg, ast.Subscript)
                        and isinstance(arg.value, ast.Name)):
                    base = arg.value.id
                if base is not None and base in tainted and base not in host:
                    label = path.split(".")[-1]
                    yield self.finding(
                        ctx, node.lineno,
                        f"`{label}({base}…)` converts a device value to "
                        f"host inside hot-path function '{fn.name}' — "
                        "each conversion is a blocking sync")

    # ---- taint tracking -------------------------------------------------
    def _track(self, ctx, value, targets, tainted: Set[str],
               host: Set[str]):
        names = [leaf.id for t in targets for leaf in ast.walk(t)
                 if isinstance(leaf, ast.Name)]
        if not names:
            return
        is_device = False
        if isinstance(value, ast.Call):
            path = ctx.resolve_call(value.func)
            is_device = not (
                path in _HOST_BUILTINS
                or any(path.startswith(p) for p in _HOST_PREFIXES))
        elif isinstance(value, ast.Name):
            is_device = value.id in tainted and value.id not in host
        for n in names:
            if is_device:
                tainted.add(n)
                host.discard(n)
            else:
                host.add(n)
                tainted.discard(n)

    def _ordered(self, fn):
        """Depth-first, source-ordered traversal of the function body."""
        out = []

        def visit(node):
            out.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)
        return out
