"""trace-purity: jit-traced functions must be pure.

XLA's correctness contract (and jax's) is that a traced function is a
pure array program: side effects run ONCE at trace time and silently
vanish from the compiled executable, wall-clock reads bake the
trace-time value into the program as a constant, and host RNG
(``random``/``np.random``) freezes one sample into every execution.
Every ``jax.jit``/``pl.pallas_call`` target in this codebase (the engine
scatter/prefill/decode steps, the Pallas kernels) must therefore avoid
host side effects; this rule makes the convention machine-checked.

Detected trace entry points:
- ``@jax.jit`` (bare, or via ``functools.partial(jax.jit, ...)``)
- ``jax.jit(fn, ...)`` where ``fn`` is a function defined in the module
- ``pl.pallas_call(kernel, ...)`` — directly on a local def, or on a
  name bound to ``functools.partial(kernel, ...)`` (the repo's idiom for
  passing compile-time attrs into a kernel)

Flagged inside a traced function (nested defs included — they execute at
trace time):
- calls into ``time.*``, stdlib ``random.*``, ``numpy.random.*``,
  ``os.urandom``, ``print``, ``input``, ``open``
- mutation of nonlocal/global state (a ``global``/``nonlocal``
  declaration whose name is assigned in the traced body)
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from ..core import Finding, ModuleContext, Rule, register_rule

_IMPURE_PREFIXES = (
    "time.", "random.", "numpy.random.", "os.urandom",
)
_IMPURE_BUILTINS = {"print", "input", "open"}
_JIT_CALLS = {"jax.jit", "jit"}
_PALLAS_SUFFIX = ".pallas_call"
_PARTIAL = {"functools.partial", "partial"}


def _is_jit_path(path: str) -> bool:
    return path in _JIT_CALLS or path.endswith(".jit") and path.startswith(
        "jax")


def _is_pallas_path(path: str) -> bool:
    return path == "pallas_call" or path.endswith(_PALLAS_SUFFIX)


@register_rule
class TracePurityRule(Rule):
    id = "trace-purity"
    rationale = ("side effects inside jit/pallas-traced code run once at "
                 "trace time and bake stale values into the compiled "
                 "program")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        defs = self._collect_defs(ctx.tree)
        partial_of = self._partial_bindings(ctx)
        traced: Set[ast.AST] = set()

        # decorator form
        for fn in defs.values():
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                path = ctx.resolve_call(target)
                if _is_jit_path(path) or _is_pallas_path(path):
                    traced.add(fn)
                elif (isinstance(dec, ast.Call) and path in _PARTIAL
                        and dec.args
                        and _is_jit_path(ctx.resolve_call(dec.args[0]))):
                    traced.add(fn)

        # call form: jax.jit(fn, ...) / pl.pallas_call(kernel, ...)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            path = ctx.resolve_call(node.func)
            if not (_is_jit_path(path) or _is_pallas_path(path)):
                continue
            first = node.args[0]
            names: List[str] = []
            if isinstance(first, ast.Name):
                names.append(first.id)
                names.extend(partial_of.get(first.id, ()))
            elif (isinstance(first, ast.Call)
                    and ctx.resolve_call(first.func) in _PARTIAL
                    and first.args and isinstance(first.args[0], ast.Name)):
                names.append(first.args[0].id)
            for n in names:
                if n in defs:
                    traced.add(defs[n])

        for fn in sorted(traced, key=lambda f: f.lineno):
            yield from self._check_traced(ctx, fn)

    # ---- helpers --------------------------------------------------------
    def _collect_defs(self, tree) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(node.name, node)
        return out

    def _partial_bindings(self, ctx: ModuleContext) -> Dict[str, List[str]]:
        """name -> [kernel names] for ``k = functools.partial(fn, ...)``."""
        out: Dict[str, List[str]] = {}
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and ctx.resolve_call(node.value.func) in _PARTIAL
                    and node.value.args
                    and isinstance(node.value.args[0], ast.Name)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, []).append(
                            node.value.args[0].id)
        return out

    def _check_traced(self, ctx: ModuleContext, fn) -> Iterable[Finding]:
        assigned: Set[str] = set()
        escaping: Dict[str, int] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                path = ctx.resolve_call(node.func)
                if path in _IMPURE_BUILTINS or any(
                        path == p.rstrip(".") or path.startswith(p)
                        for p in _IMPURE_PREFIXES):
                    yield self.finding(
                        ctx, node.lineno,
                        f"impure call {path}() inside jit/pallas-traced "
                        f"function '{fn.name}' — runs at trace time, not "
                        "per execution")
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                for name in node.names:
                    escaping.setdefault(name, node.lineno)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            assigned.add(leaf.id)
        for name, line in sorted(escaping.items(), key=lambda kv: kv[1]):
            if name in assigned:
                yield self.finding(
                    ctx, line,
                    f"traced function '{fn.name}' mutates nonlocal/global "
                    f"'{name}' — the write happens once at trace time")
