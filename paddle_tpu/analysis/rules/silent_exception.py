"""silent-exception: broad handlers must not swallow errors invisibly.

``except Exception: pass`` in a serving or distributed code path turns a
real fault (a cancelled request that didn't cancel, a trace export that
never happened, a store write that was lost) into silence — the failure
mode that costs the most to debug because there is nothing to debug
FROM. The fix hierarchy: narrow the exception type to what the code
actually expects, or log through the rank-aware logger
(``distributed.log_utils.get_logger``) so multihost lines stay
attributable; a handler that is deliberately silent carries an inline
``# pdlint: disable=silent-exception`` pragma with a comment saying why.

Flagged: a handler catching a BROAD type (bare ``except``,
``Exception``, ``BaseException`` — alone or in a tuple) whose body
neither raises nor calls anything (no logging, no cleanup, no recovery —
just ``pass``/constants/trivial assignments). Narrow handlers
(``except queue.Empty: pass``) are legal: naming the exact exception IS
the documentation.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, ModuleContext, Rule, register_rule

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    return False


def _is_silent(body) -> bool:
    """True when the handler neither raises nor calls anything — no log,
    no cleanup, no recovery path."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Call, ast.Yield,
                                 ast.YieldFrom, ast.Await)):
                return False
    return True


@register_rule
class SilentExceptionRule(Rule):
    id = "silent-exception"
    rationale = ("a broad except that neither logs nor re-raises makes "
                 "real faults (cancel/trace/export failures) vanish")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type) and _is_silent(node.body):
                caught = (ast.unparse(node.type) if node.type is not None
                          else "<bare except>")
                yield self.finding(
                    ctx, node.lineno,
                    f"broad handler ({caught}) silently swallows the "
                    "error — narrow the type or log via "
                    "distributed.log_utils.get_logger()")
