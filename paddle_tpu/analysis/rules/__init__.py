"""Rule modules — importing this package registers every rule into
``core.RULES``. Add a rule by adding a module here and importing it;
docs/ANALYSIS.md carries the per-rule catalog."""
from . import trace_purity  # noqa: F401
from . import host_sync  # noqa: F401
from . import lock_discipline  # noqa: F401
from . import silent_exception  # noqa: F401
from . import op_schema  # noqa: F401
from . import catalogs  # noqa: F401
from . import pragmas  # noqa: F401
from . import fused_coverage  # noqa: F401
from ..graph import rules as graph_rules  # noqa: F401
from ..threads import rules as thread_rules  # noqa: F401
from ..lifecycle import rules as lifecycle_rules  # noqa: F401
from ..errflow import rules as errflow_rules  # noqa: F401
