"""``unused-disable``: pragma hygiene.

A ``# pdlint: disable=<id>`` that suppresses nothing is worse than
noise — it documents a violation that no longer exists (or never did,
when the id is a typo), and it will silently swallow the NEXT real
finding on that line. Core tracks which pragmas actually fired
(``ModuleContext.pragma_used``); this rule only declares the id and
rationale for the catalog. The findings themselves are produced by
``core.unused_pragma_findings`` after all selected rules have run,
because "unused" is only decidable once every rule has had its chance
to use the pragma. Ids of rules that did NOT run this invocation are
never flagged — a ``leak-path`` pragma is live documentation even on a
default, non-``--lifecycle`` pass.
"""
from __future__ import annotations

from typing import Iterable

from ..core import Finding, ModuleContext, Rule, register_rule

__all__ = ["UnusedDisableRule"]


@register_rule
class UnusedDisableRule(Rule):
    id = "unused-disable"
    rationale = ("a disable pragma that suppresses nothing documents a "
                 "violation that no longer exists and will silently "
                 "swallow the next real finding on its line")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        # driver-computed (core.unused_pragma_findings): needs the
        # whole run's pragma-usage state, not one rule's pass
        return ()
