"""Control-flow graphs over Python AST — the lifecycle layer's substrate.

The AST rules so far pattern-match single statements; the resource-leak
class (``lifecycle/``) is a *path* property: "is the release reachable
from the acquire on EVERY path out of the function, including the
exception edges". Answering that needs a real CFG, so this module builds
one — per function, statement-granular, with the edges that matter for
unwind reasoning:

- branch edges (``true``/``false``) for ``if``/``while``/``for`` heads
  (a ``while True:`` head emits no ``false`` edge);
- loop back-edges (``loop``), ``break``/``continue`` edges routed to a
  lazily-created ``loopexit`` node so abrupt loop exits stay distinct
  from normal exhaustion;
- ``raise`` edges from every statement that can raise (any statement
  containing a call — the caller may pass a ``noraise`` allowlist of
  resolved call paths that are trusted not to throw) to the innermost
  handler dispatch, else to the function's ``raise`` exit;
- ``try``/``except``/``else``/``finally``: a lazy ``except`` dispatch
  node chains handlers in order (``except`` into the first, ``nomatch``
  between them, a final ``raise`` edge out unless the last handler is
  broad); ``finally`` bodies are DUPLICATED per continuation kind
  (normal / raise / return / break / continue), exactly the way
  compilers lower them, so a ``return`` inside ``try`` correctly runs
  the finally copy and then leaves via a ``return`` edge while the
  normal path runs its own copy and falls through;
- ``with``: the head node owns the context expressions (and their
  ``raise`` edge); body statements keep their own raise edges — the
  manager's ``__exit__`` runs on that unwind implicitly, which is why
  the lifecycle pass treats ``with``-bound resources as managed.

Three exits per graph: ``entry``, ``exit`` (normal return / fall-off),
and ``raise`` (an exception escaping the function). Nested function and
class bodies are opaque single statements (they execute at *call* time,
not here); calls inside ``lambda``/nested ``def`` bodies never produce
raise edges for the enclosing function.

Pure ``ast`` — no paddle_tpu import — so fixture snippets unit-test the
builder in isolation (tests/test_lifecycle_analysis.py), and future rule
families (the PR-19 adapter-registry checks) can reuse it as-is.
"""
from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Tuple

__all__ = ["ControlFlowGraph", "CFGNode", "build_cfg", "function_nodes",
           "may_raise"]


class CFGNode:
    """One CFG node: a statement (or a synthetic head/exit marker).

    ``kind`` is one of ``entry``/``exit``/``raise`` (the three boundary
    nodes), ``stmt`` (a simple statement), ``branch`` (an ``if`` test),
    ``loop`` (a ``while``/``for`` head), ``with`` (a ``with`` head),
    ``except`` (a handler-dispatch point), ``handler`` (one ``except``
    clause head), ``finally`` (the entry of one duplicated finally
    copy), ``loopexit`` (the landing point of ``break``). ``stmt`` holds
    the originating AST node (shared between finally copies)."""

    __slots__ = ("id", "kind", "stmt", "line")

    def __init__(self, nid: int, kind: str, stmt: Optional[ast.AST]):
        self.id = nid
        self.kind = kind
        self.stmt = stmt
        self.line = getattr(stmt, "lineno", 0)

    @property
    def label(self) -> str:
        if self.kind in ("entry", "exit", "raise"):
            return self.kind
        return f"{self.kind}@{self.line}"

    def __repr__(self):
        return f"CFGNode({self.label})"


class ControlFlowGraph:
    """Nodes + labeled edges + the three boundary nodes."""

    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.nodes: Dict[int, CFGNode] = {}
        self._succ: Dict[int, List[Tuple[int, str]]] = {}
        self._pred: Dict[int, List[Tuple[int, str]]] = {}
        self.entry = self._add("entry", None)
        self.exit = self._add("exit", None)
        self.raise_exit = self._add("raise", None)

    def _add(self, kind: str, stmt) -> int:
        nid = len(self.nodes)
        self.nodes[nid] = CFGNode(nid, kind, stmt)
        self._succ[nid] = []
        self._pred[nid] = []
        return nid

    def add_edge(self, src: int, dst: int, kind: str):
        if (dst, kind) not in self._succ[src]:
            self._succ[src].append((dst, kind))
            self._pred[dst].append((src, kind))

    def succ(self, nid: int) -> List[Tuple[int, str]]:
        return self._succ[nid]

    def pred(self, nid: int) -> List[Tuple[int, str]]:
        return self._pred[nid]

    def edge_labels(self) -> set:
        """``{(src.label, kind, dst.label)}`` — the unit-test surface.
        Finally copies share a label (same source line), which is fine
        for membership assertions."""
        return {(self.nodes[s].label, kind, self.nodes[d].label)
                for s in self._succ for (d, kind) in self._succ[s]}

    def stmt_nodes(self) -> Iterable[CFGNode]:
        return (n for n in self.nodes.values() if n.stmt is not None)


# ---- may-raise classification ----------------------------------------------

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)


def _eager_nodes(node: ast.AST) -> Iterable[ast.AST]:
    """Sub-nodes evaluated when ``node`` executes — nested function/
    lambda/class bodies are skipped (they run later, elsewhere); a
    ``def``/``class`` statement itself evaluates only its decorators,
    defaults, and bases now."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        roots: List[ast.AST] = list(node.decorator_list)
        roots += [d for d in node.args.defaults]
        roots += [d for d in node.args.kw_defaults if d is not None]
    elif isinstance(node, ast.ClassDef):
        roots = list(node.decorator_list) + list(node.bases) \
            + [k.value for k in node.keywords]
    else:
        roots = [node]
    stack = list(roots)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _SCOPE_BARRIERS):
            continue
        stack.extend(ast.iter_child_nodes(n))


def may_raise(node: ast.AST,
              resolver: Optional[Callable[[ast.AST], str]] = None,
              noraise: FrozenSet[str] = frozenset()) -> bool:
    """Conservative: a statement/expression can raise iff it contains a
    call (or an ``await``) outside nested scopes. ``resolver`` +
    ``noraise`` whitelist resolved call paths trusted not to throw
    (loggers, monotonic clocks, metric counters) so the leak pass does
    not report a leak path through ``log.info``."""
    for n in _eager_nodes(node):
        if isinstance(n, ast.Await):
            return True
        if isinstance(n, ast.Call):
            if resolver is not None and noraise:
                name = resolver(n.func)
                if name and (name in noraise
                             or name.rsplit(".", 1)[-1] in noraise):
                    continue
            return True
    return False


# ---- builder ---------------------------------------------------------------

class _Target:
    """A lazily-materialized jump target: finally copies (and loop-exit
    landing nodes) are built only when something actually jumps there,
    so a try without a break never grows a break-finally copy."""

    __slots__ = ("_make", "_id")

    def __init__(self, make: Callable[[], int]):
        self._make = make
        self._id: Optional[int] = None

    def __call__(self) -> int:
        if self._id is None:
            self._id = self._make()
        return self._id

    @property
    def created(self) -> bool:
        return self._id is not None


def _const(nid: int) -> _Target:
    t = _Target(lambda: nid)
    return t


class _Ctx:
    """Where abrupt completions go from the current position."""

    __slots__ = ("raise_to", "return_to", "break_to", "continue_to")

    def __init__(self, raise_to, return_to, break_to, continue_to):
        self.raise_to = raise_to
        self.return_to = return_to
        self.break_to = break_to
        self.continue_to = continue_to

    def replace(self, **kw) -> "_Ctx":
        vals = {s: getattr(self, s) for s in self.__slots__}
        vals.update(kw)
        return _Ctx(**vals)


Frontier = List[Tuple[int, str]]


class _Builder:
    def __init__(self, resolver, noraise):
        self.resolver = resolver
        self.noraise = noraise
        self.cfg: ControlFlowGraph = None  # set in build

    # -- helpers ----------------------------------------------------------
    def _new(self, kind: str, stmt) -> int:
        return self.cfg._add(kind, stmt)

    def _connect(self, frontier: Frontier, dst: int,
                 kind: Optional[str] = None):
        for (src, k) in frontier:
            self.cfg.add_edge(src, dst, kind if kind is not None else k)

    def _raises(self, node) -> bool:
        return may_raise(node, self.resolver, self.noraise)

    # -- entry ------------------------------------------------------------
    def build(self, func) -> ControlFlowGraph:
        self.cfg = ControlFlowGraph(func.name, func.lineno)
        ctx = _Ctx(raise_to=_const(self.cfg.raise_exit),
                   return_to=_const(self.cfg.exit),
                   break_to=None, continue_to=None)
        frontier = self._seq(func.body, [(self.cfg.entry, "next")], ctx)
        self._connect(frontier, self.cfg.exit)
        return self.cfg

    def _seq(self, stmts, frontier: Frontier, ctx: _Ctx) -> Frontier:
        for stmt in stmts:
            frontier = self._stmt(stmt, frontier, ctx)
        return frontier

    # -- statement dispatch ------------------------------------------------
    def _stmt(self, stmt, frontier: Frontier, ctx: _Ctx) -> Frontier:
        handler = getattr(self, f"_stmt_{type(stmt).__name__}", None)
        if handler is not None:
            return handler(stmt, frontier, ctx)
        return self._simple(stmt, frontier, ctx)

    def _simple(self, stmt, frontier: Frontier, ctx: _Ctx) -> Frontier:
        n = self._new("stmt", stmt)
        self._connect(frontier, n)
        if self._raises(stmt):
            self.cfg.add_edge(n, ctx.raise_to(), "raise")
        return [(n, "next")]

    def _stmt_Return(self, stmt, frontier, ctx):
        n = self._new("stmt", stmt)
        self._connect(frontier, n)
        if stmt.value is not None and self._raises(stmt.value):
            self.cfg.add_edge(n, ctx.raise_to(), "raise")
        self.cfg.add_edge(n, ctx.return_to(), "return")
        return []

    def _stmt_Raise(self, stmt, frontier, ctx):
        n = self._new("stmt", stmt)
        self._connect(frontier, n)
        self.cfg.add_edge(n, ctx.raise_to(), "raise")
        return []

    def _stmt_Break(self, stmt, frontier, ctx):
        n = self._new("stmt", stmt)
        self._connect(frontier, n)
        if ctx.break_to is not None:
            self.cfg.add_edge(n, ctx.break_to(), "break")
        return []

    def _stmt_Continue(self, stmt, frontier, ctx):
        n = self._new("stmt", stmt)
        self._connect(frontier, n)
        if ctx.continue_to is not None:
            self.cfg.add_edge(n, ctx.continue_to(), "continue")
        return []

    def _stmt_Assert(self, stmt, frontier, ctx):
        n = self._new("stmt", stmt)
        self._connect(frontier, n)
        self.cfg.add_edge(n, ctx.raise_to(), "raise")
        return [(n, "next")]

    def _stmt_If(self, stmt, frontier, ctx):
        n = self._new("branch", stmt)
        self._connect(frontier, n)
        if self._raises(stmt.test):
            self.cfg.add_edge(n, ctx.raise_to(), "raise")
        out = self._seq(stmt.body, [(n, "true")], ctx)
        if stmt.orelse:
            out = out + self._seq(stmt.orelse, [(n, "false")], ctx)
        else:
            out = out + [(n, "false")]
        return out

    def _loop(self, stmt, frontier, ctx, test_raises: bool,
              always_enters: bool):
        head = self._new("loop", stmt)
        self._connect(frontier, head)
        if test_raises:
            self.cfg.add_edge(head, ctx.raise_to(), "raise")
        brk = _Target(lambda: self._new("loopexit", stmt))
        body_ctx = ctx.replace(break_to=brk, continue_to=_const(head))
        body = self._seq(stmt.body, [(head, "true")], body_ctx)
        self._connect(body, head, kind="loop")
        out: Frontier = []
        if not always_enters:
            if stmt.orelse:
                out += self._seq(stmt.orelse, [(head, "false")], ctx)
            else:
                out += [(head, "false")]
        if brk.created:
            out += [(brk(), "next")]
        return out

    def _stmt_While(self, stmt, frontier, ctx):
        infinite = (isinstance(stmt.test, ast.Constant)
                    and stmt.test.value is True)
        return self._loop(stmt, frontier, ctx,
                          test_raises=self._raises(stmt.test),
                          always_enters=infinite)

    def _stmt_For(self, stmt, frontier, ctx):
        return self._loop(stmt, frontier, ctx,
                          test_raises=self._raises(stmt.iter),
                          always_enters=False)

    _stmt_AsyncFor = _stmt_For

    def _stmt_With(self, stmt, frontier, ctx):
        head = self._new("with", stmt)
        self._connect(frontier, head)
        if any(self._raises(item.context_expr) for item in stmt.items):
            self.cfg.add_edge(head, ctx.raise_to(), "raise")
        return self._seq(stmt.body, [(head, "with")], ctx)

    _stmt_AsyncWith = _stmt_With

    def _stmt_Try(self, stmt, frontier, ctx):
        octx = ctx
        if stmt.finalbody:
            # one lazily-built finally COPY per continuation kind, each
            # flowing on to the outer target with that kind's edge
            def fin(outer: Optional[_Target], kind: str):
                if outer is None:
                    return None

                def make() -> int:
                    entry = self._new("finally", stmt.finalbody[0])
                    f = self._seq(stmt.finalbody, [(entry, "next")], octx)
                    self._connect(f, outer(), kind=kind)
                    return entry
                return _Target(make)

            ctx = _Ctx(raise_to=fin(octx.raise_to, "raise"),
                       return_to=fin(octx.return_to, "return"),
                       break_to=fin(octx.break_to, "break"),
                       continue_to=fin(octx.continue_to, "continue"))
        body_ctx = ctx
        dispatch = None
        if stmt.handlers:
            dispatch = _Target(lambda: self._new("except", stmt))
            body_ctx = ctx.replace(raise_to=dispatch)
        out = self._seq(stmt.body, frontier, body_ctx)
        if stmt.orelse:
            out = self._seq(stmt.orelse, out, ctx)
        if dispatch is not None and dispatch.created:
            prev: Frontier = [(dispatch(), "except")]
            caught_all = False
            for h in stmt.handlers:
                hn = self._new("handler", h)
                self._connect(prev, hn)
                out = out + self._seq(h.body, [(hn, "caught")], ctx)
                prev = [(hn, "nomatch")]
                if _is_broad_handler(h.type):
                    caught_all = True
                    prev = []
                    break
            if prev and not caught_all:
                # no handler matched: the exception keeps unwinding
                # (through the finally, when there is one)
                self._connect(prev, ctx.raise_to(), kind="raise")
        if stmt.finalbody:
            # normal completion runs its own finally copy and falls out
            entry = self._new("finally", stmt.finalbody[0])
            self._connect(out, entry)
            out = self._seq(stmt.finalbody, [(entry, "next")], octx)
        return out


def _is_broad_handler(type_node) -> bool:
    """``except:`` / ``except Exception:`` / ``except BaseException:``
    (alone or in a tuple) stop the unwind for everything the leak pass
    reasons about."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad_handler(e) for e in type_node.elts)
    name = type_node.attr if isinstance(type_node, ast.Attribute) else (
        type_node.id if isinstance(type_node, ast.Name) else "")
    return name in ("Exception", "BaseException")


def build_cfg(func: ast.AST,
              resolver: Optional[Callable[[ast.AST], str]] = None,
              noraise: FrozenSet[str] = frozenset()) -> ControlFlowGraph:
    """CFG for one ``FunctionDef``/``AsyncFunctionDef``."""
    return _Builder(resolver, noraise).build(func)


def function_nodes(tree: ast.AST):
    """Every function in a module, outermost-first, with its qualname —
    nested defs included (their bodies are opaque in the ENCLOSING
    function's CFG but get their own graph here)."""
    out = []

    def visit(node, qual):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                out.append((q, child))
                visit(child, q)
            elif isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                visit(child, q)
            else:
                visit(child, qual)

    visit(tree, "")
    return out
