"""errflow — interprocedural exception-flow analysis (``pdlint
--errors``).

Per-function exception summaries (which types can escape, with
raise-site provenance) computed by a call-graph fixpoint that composes
the PR-18 CFG (handler-dispatch edges for catch/narrow/re-raise) with
the PR-9 whole-program thread model, plus the typed-error lattice and
the HTTP error taxonomy. See docs/ANALYSIS.md "Exception-flow
analysis"; the rules live in ``rules.py`` and the tier-1 gate in
tests/test_errflow_analysis.py.
"""
from .lattice import ErrorLattice  # noqa: F401
from .summaries import ErrorFlow, get_flow  # noqa: F401
from .taxonomy import NON_RETRYABLE, RETRYABLE, TAXONOMY  # noqa: F401

__all__ = ["ErrorLattice", "ErrorFlow", "get_flow", "TAXONOMY",
           "RETRYABLE", "NON_RETRYABLE"]
