"""The ``--errors`` rules: typed-error contract enforcement over the
exception summaries.

Four rules, all whole-program (``ProjectRule``), all gated behind
``pdlint --errors`` exactly like ``--graph``/``--threads``/
``--lifecycle`` gate theirs, with baseline/ratchet/SARIF/``--select``
riding the existing machinery:

- **error-thread-escape** — an exception can escape a thread root from
  the PR-9 thread model uncaught: the thread dies silently and the
  daemon it implemented (engine loop, supervisor monitor, ts-sampler,
  heartbeat republisher, handoff drain) just... stops. Typed
  (control/fault) escapes are named with raise-site provenance; a
  generic-only escape set still fires — it means the root has at least
  one call path with no guard at all. Fatal types
  (KeyboardInterrupt) are exempt — crashing loud is their contract.
- **error-http-contract** — the docs/SERVING.md "Error taxonomy" table
  against ``taxonomy.TAXONOMY`` against the actual emit sites, all
  directions (see taxonomy.py).
- **error-swallow** — a broad ``except`` whose arrival set (per the
  summaries) includes a typed exception it neither re-raises nor maps:
  swallowing a control-flow type breaks the router protocol outright;
  swallowing a fault type without even referencing the bound exception
  loses the typed contract invisibly. The type-aware upgrade of
  ``silent-exception``.
- **error-retry-unsafe** — a retry/failover loop that can re-dispatch
  after catching an error the taxonomy marks non-retryable (a global
  deadline cannot be un-expired by another replica; a quarantined
  request must never be placed again).

Scope is the serving tier + observability (the lifecycle scope);
fixture files outside ``paddle_tpu/`` are always checked so the tests
can stage both sides of every rule.
"""
from __future__ import annotations

import ast
import os
from typing import Iterable, List, Tuple

from ..core import Finding, ProjectRule, register_rule
from ..lifecycle.rules import _in_scope
from ..threads.model import ProjectModel, get_model
from . import taxonomy as tax
from .lattice import handler_spec
from .summaries import ErrorFlow, get_flow

__all__ = ["thread_escape_findings", "swallow_findings",
           "retry_unsafe_findings", "http_contract_findings",
           "scope_roots"]

_DOCS = os.path.join("docs", "SERVING.md")

# the emit-site scan is serving-tier only: that is where responses are
# assembled ("code" dict literals elsewhere would be coincidences)
_EMIT_PREFIX = "paddle_tpu/serving"


def scope_roots(model: ProjectModel) -> List[Tuple[str, str]]:
    """What the engine analyzes: every function in an in-scope file
    plus every resolved spawn target (roots pull their out-of-scope
    callees in through the call graph)."""
    roots = [key for key, fn in sorted(model.functions.items())
             if _in_scope(fn.file)]
    roots += [sp.target for sp in model.spawn_sites
              if sp.target is not None]
    return roots


def _suppressed(model: ProjectModel, file: str, line: int,
                rule_id: str) -> bool:
    mod = model.modules.get(file)
    return mod is not None and mod.ctx.suppressed(line, rule_id)


def _symbol(model: ProjectModel, file: str, line: int) -> str:
    mod = model.modules.get(file)
    return mod.ctx.symbol_for_line(line) if mod is not None else ""


def _fmt_types(typed) -> str:
    return ", ".join(f"{t} (from {o[0]}:{o[1]})"
                     for t, o in sorted(typed.items()))


# ---- error-thread-escape ----------------------------------------------------

def thread_escape_findings(model: ProjectModel, flow: ErrorFlow,
                           rule_id: str = "error-thread-escape"
                           ) -> List[Finding]:
    out = []
    for sp in model.spawn_sites:
        if sp.target is None or not _in_scope(sp.file):
            continue
        escapes = flow.escapes_of(sp.target)
        nonfatal = {t: o for t, o in escapes.items()
                    if flow.lattice.classify(t) != "fatal"}
        if not nonfatal:
            continue
        if _suppressed(model, sp.file, sp.line, rule_id):
            continue
        _tfile, tqual = sp.target
        typed = flow.typed(nonfatal)
        if typed:
            what = f"uncaught {_fmt_types(typed)}"
        else:
            what = ("any uncaught exception (unguarded call paths in "
                    "the loop body)")
        out.append(Finding(
            file=sp.file, line=sp.line, rule=rule_id,
            symbol=_symbol(model, sp.file, sp.line),
            message=(f"thread '{sp.thread_name}' root {tqual}() can die "
                     f"on {what} — a silently-dead "
                     "daemon thread; catch at the root (log, recover or "
                     "re-arm) or pragma a deliberate crash boundary"),
            data={"target": list(sp.target),
                  "escapes": {t: {"file": o[0], "line": o[1]}
                              for t, o in sorted(nonfatal.items())}}))
    return out


# ---- error-swallow ----------------------------------------------------------

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _handler_walk(handler: ast.ExceptHandler):
    stack = list(handler.body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _SCOPE_BARRIERS):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in _handler_walk(handler))


def _uses_bound_name(handler: ast.ExceptHandler) -> bool:
    if not handler.name:
        return False
    return any(isinstance(n, ast.Name) and n.id == handler.name
               for n in _handler_walk(handler))


def swallow_findings(model: ProjectModel, flow: ErrorFlow,
                     rule_id: str = "error-swallow") -> List[Finding]:
    out = []
    for file in sorted(model.modules):
        if not _in_scope(file):
            continue
        mod = model.modules[file]
        for node in ast.walk(mod.ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            _names, broad = handler_spec(node.type, mod.ctx.resolve_call)
            if not broad:
                continue
            typed = flow.typed(flow.handler_arrivals.get(id(node), {}))
            if not typed or _reraises(node):
                continue
            control = {t: o for t, o in typed.items()
                       if flow.lattice.classify(t) == "control"}
            if control:
                what, types = "control-flow", control
                hint = ("handle it by type before the broad clause or "
                        "re-raise — swallowing it breaks the routing "
                        "protocol")
            elif not _uses_bound_name(node):
                what, types = "typed", typed
                hint = ("bind the exception and map it to its "
                        "documented response (docs/SERVING.md 'Error "
                        "taxonomy'), or narrow the except")
            else:
                continue
            if _suppressed(model, file, node.lineno, rule_id):
                continue
            caught_txt = (ast.unparse(node.type) if node.type is not None
                          else "<bare except>")
            out.append(Finding(
                file=file, line=node.lineno, rule=rule_id,
                symbol=_symbol(model, file, node.lineno),
                message=(f"broad handler ({caught_txt}) swallows {what} "
                         f"exception(s) {_fmt_types(types)} — {hint}"),
                data={"swallowed": {t: {"file": o[0], "line": o[1]}
                                    for t, o in sorted(types.items())}}))
    return out


# ---- error-retry-unsafe -----------------------------------------------------

def _try_loops(fn_node) -> List[Tuple[ast.Try, ast.AST]]:
    """Every ``try`` with its nearest enclosing loop, nested defs
    excluded (they are their own functions)."""
    out = []

    def walk(node, loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_BARRIERS):
                continue
            nl = child if isinstance(child, (ast.While, ast.For,
                                             ast.AsyncFor)) else loop
            if isinstance(child, ast.Try) and nl is not None:
                out.append((child, nl))
            walk(child, nl)

    walk(fn_node, None)
    return out


def _handler_rejoins_loop(cfg, handler_ast, loop_ast) -> bool:
    """CFG reachability: from the handler's body, can control reach the
    loop head again (fall-through to the back-edge, or ``continue``)
    without leaving the function? ``return``/``break``/``raise`` paths
    don't count as re-dispatch."""
    hid = lid = None
    for n in cfg.nodes.values():
        if n.kind == "handler" and n.stmt is handler_ast:
            hid = n.id
        elif n.kind == "loop" and n.stmt is loop_ast:
            lid = n.id
    if hid is None or lid is None:
        return False
    stack = [d for (d, k) in cfg.succ(hid) if k == "caught"]
    seen = set(stack)
    while stack:
        n = stack.pop()
        if n == lid:
            return True
        for (d, k) in cfg.succ(n):
            if k != "raise" and d not in seen:
                seen.add(d)
                stack.append(d)
    return False


def retry_unsafe_findings(model: ProjectModel, flow: ErrorFlow,
                          rule_id: str = "error-retry-unsafe"
                          ) -> List[Finding]:
    out = []
    for file in sorted(model.modules):
        if not _in_scope(file):
            continue
        mod = model.modules[file]
        for qual in sorted(mod.functions):
            fn = mod.functions[qual]
            pairs = _try_loops(fn.node)
            if not pairs:
                continue
            cfg = flow.function_cfg(fn.key)
            for (try_stmt, loop) in pairs:
                for handler in try_stmt.handlers:
                    names, broad = handler_spec(handler.type,
                                                mod.ctx.resolve_call)
                    arr = flow.handler_arrivals.get(id(handler), {})
                    bad = ({t for t in arr if t in tax.NON_RETRYABLE}
                           | {n for n in names if n in tax.NON_RETRYABLE})
                    if not bad:
                        continue
                    if not _handler_rejoins_loop(cfg, handler, loop):
                        continue
                    if _suppressed(model, file, handler.lineno, rule_id):
                        continue
                    bad_txt = ", ".join(sorted(bad))
                    out.append(Finding(
                        file=file, line=handler.lineno, rule=rule_id,
                        symbol=_symbol(model, file, handler.lineno),
                        message=(f"retry loop can re-dispatch after "
                                 f"catching non-retryable {bad_txt} "
                                 "(docs/SERVING.md 'Error taxonomy') — "
                                 "answer the client and return instead "
                                 "of burning a retry on an error no "
                                 "replica can fix"),
                        data={"non_retryable": sorted(bad),
                              "loop_line": loop.lineno}))
    return out


# ---- error-http-contract ----------------------------------------------------

def http_contract_findings(model: ProjectModel, root: str,
                           rule_id: str = "error-http-contract"
                           ) -> List[Finding]:
    docs_path = os.path.join(root, _DOCS)
    docs = (tax.documented_taxonomy(docs_path)
            if os.path.isfile(docs_path) else {})
    trees = {f: m.ctx.tree for f, m in model.modules.items()
             if f.startswith(_EMIT_PREFIX)}
    problems = tax.compare_taxonomy(
        docs, tax.TAXONOMY,
        known_classes=set(model.classes_by_name),
        codes_emitted=tax.emitted_codes(trees),
        statuses_emitted=tax.emitted_statuses(trees))
    return [Finding(file=_DOCS.replace(os.sep, "/"), line=1,
                    rule=rule_id, message=msg, symbol="error-taxonomy")
            for msg in problems]


# ---- registration -----------------------------------------------------------

class _ErrorRule(ProjectRule):
    """Base: exception-flow rules opt in via ``--errors``."""

    errors = True

    def _findings(self, model: ProjectModel, flow: ErrorFlow,
                  root: str) -> List[Finding]:
        raise NotImplementedError

    def check_project(self, root: str) -> Iterable[Finding]:
        model = get_model(root)
        flow = get_flow(model)
        flow.analyze(scope_roots(model))
        return self._findings(model, flow, root)


@register_rule
class ErrorThreadEscapeRule(_ErrorRule):
    id = "error-thread-escape"
    rationale = ("an exception escaping a thread root kills the daemon "
                 "silently — the sampler/monitor/republisher just "
                 "stops; every root catches, logs, and decides")

    def _findings(self, model, flow, root):
        return thread_escape_findings(model, flow, self.id)


@register_rule
class ErrorHttpContractRule(_ErrorRule):
    id = "error-http-contract"
    rationale = ("the typed error ↔ HTTP status ↔ code= ↔ retryable "
                 "contract must match docs, taxonomy, and the actual "
                 "emit sites, all directions — clients program against "
                 "it")

    def _findings(self, model, flow, root):
        return http_contract_findings(model, root, self.id)


@register_rule
class ErrorSwallowRule(_ErrorRule):
    id = "error-swallow"
    rationale = ("a broad except that swallows a typed control-flow or "
                 "fault exception un-types the error contract — the "
                 "type-aware upgrade of silent-exception")

    def _findings(self, model, flow, root):
        return swallow_findings(model, flow, self.id)


@register_rule
class ErrorRetryUnsafeRule(_ErrorRule):
    id = "error-retry-unsafe"
    rationale = ("re-dispatching after a non-retryable error (expired "
                 "deadline, quarantined request, client error) wastes "
                 "capacity and can double-execute — the taxonomy marks "
                 "what a retry can never fix")

    def _findings(self, model, flow, root):
        return retry_unsafe_findings(model, flow, self.id)
