"""The typed-error taxonomy: class ↔ HTTP status ↔ ``code=`` ↔ retryable.

The single source of truth the ``error-http-contract`` rule enforces
three ways (mirroring the metric/span/event/alert catalog lints):

1. **docs** — every entry here has a row in docs/SERVING.md's "Error
   taxonomy" table with matching status/code/retryable cells, and every
   documented row names an entry here (both directions);
2. **classes** — every entry's error class exists in the project class
   index (pseudo-entries in parentheses, like ``(quarantine)``, name a
   guard rather than an exception and skip this leg);
3. **emit sites** — every ``code=`` string here is actually emitted in
   the serving tier (a ``"code": "..."`` dict literal or a
   ``body["code"] = "..."`` store), every emitted code string is in the
   taxonomy, and every concrete status here appears at a
   ``._json(<status>, ...)`` response site.

``RETRYABLE``/``NON_RETRYABLE`` feed the ``error-retry-unsafe`` rule:
a failover loop must not re-dispatch after catching a non-retryable
error (a global deadline cannot be un-expired by another replica; a
quarantined request must never be placed again).
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["TaxonomyEntry", "TAXONOMY", "NON_RETRYABLE", "RETRYABLE",
           "documented_taxonomy", "compare_taxonomy", "emitted_codes",
           "emitted_statuses"]


@dataclasses.dataclass(frozen=True)
class TaxonomyEntry:
    """One row of the error contract.

    ``status`` is None when the error never maps to a response of its
    own (``_ClientGone`` — nobody left to answer; ``_Migrated`` — the
    relay continues) or when it forwards a dynamic status
    (``_ClientError`` re-emits the worker's 4xx, documented "4xx").
    ``code`` is the ``code=`` body field, "" when the body carries none.
    """

    cls: str
    status: Optional[int]
    status_doc: str           # the docs cell: "429", "4xx", "—"
    code: str
    retryable: bool
    kind: str                 # backpressure/deadline/degrade/...
    note: str

    @property
    def is_pseudo(self) -> bool:
        """Guard rows like ``(quarantine)`` — no exception class."""
        return self.cls.startswith("(")


TAXONOMY: Tuple[TaxonomyEntry, ...] = (
    TaxonomyEntry("QueueFull", 429, "429", "", True, "backpressure",
                  "bounded admission queue; Retry-After is computed"),
    TaxonomyEntry("XlaOom", 429, "429", "engine_degraded", True,
                  "degrade",
                  "device OOM tripped the degrade ladder; retry after "
                  "Retry-After"),
    TaxonomyEntry("DeadlineExceeded", 504, "504", "deadline_exceeded",
                  False, "deadline",
                  "the request's SLO budget ran out in the engine"),
    TaxonomyEntry("HandoffCorrupt", 500, "500", "", True, "migration",
                  "KV bundle failed checksum/schema checks; a fresh "
                  "export succeeds"),
    TaxonomyEntry("_WorkerBusy", 429, "429", "", True, "control",
                  "worker 429 is placement feedback — try another "
                  "replica, don't burn the retry budget"),
    TaxonomyEntry("_UpstreamError", 502, "502", "", True, "control",
                  "transport death / 5xx / mid-stream EOF; another "
                  "worker may not share it"),
    TaxonomyEntry("_ClientError", None, "4xx", "", False, "control",
                  "the worker judged the request invalid; forwarded "
                  "verbatim — bad on every replica"),
    TaxonomyEntry("_ClientGone", None, "—", "", False, "control",
                  "downstream client disconnected; nothing to answer"),
    TaxonomyEntry("_DeadlineExpired", 504, "504", "deadline_exceeded",
                  False, "control",
                  "SLO budget ran out at the router; terminal"),
    TaxonomyEntry("_Migrated", None, "—", "", True, "control",
                  "planned migration hop; the relay continues on the "
                  "destination"),
    TaxonomyEntry("(quarantine)", 422, "422", "request_quarantined",
                  False, "guard",
                  "request id implicated in >= 2 worker deaths; never "
                  "placed again"),
)

NON_RETRYABLE: Set[str] = {e.cls for e in TAXONOMY if not e.retryable}
RETRYABLE: Set[str] = {e.cls for e in TAXONOMY if e.retryable}

# | `QueueFull` | 429 | — | yes | backpressure | note |
_ROW = re.compile(
    r"^\|\s*`?\(?([A-Za-z_][A-Za-z0-9_]*)\)?`?\s*"
    r"\|\s*([0-9]{3}|4xx|—)\s*"
    r"\|\s*(?:`([a-z_]+)`|—)\s*"
    r"\|\s*(yes|no)\s*\|")


def documented_taxonomy(path: str, section: str = "Error taxonomy"
                        ) -> Dict[str, Tuple[str, str, bool]]:
    """{class: (status_cell, code, retryable)} parsed from the docs
    table (section matched the way every catalog lint matches its
    section header)."""
    out: Dict[str, Tuple[str, str, bool]] = {}
    in_section = False
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line.startswith("#"):
                in_section = line.lstrip("#").strip() == section
                continue
            if not in_section:
                continue
            m = _ROW.match(line)
            if not m:
                continue
            name, status, code, retry = m.groups()
            if name == "error":
                continue          # the header row
            key = f"({name})" if f"({name})" in {e.cls for e in TAXONOMY} \
                else name
            out[key] = (status, code or "", retry == "yes")
    return out


def compare_taxonomy(docs: Dict[str, Tuple[str, str, bool]],
                     entries: Tuple[TaxonomyEntry, ...],
                     known_classes: Set[str],
                     codes_emitted: Set[str],
                     statuses_emitted: Set[int]) -> List[str]:
    """The pure comparison core (fixture-testable without the repo):
    docs ↔ taxonomy both ways with per-cell drift, classes exist,
    codes and statuses actually emitted, emitted codes documented."""
    problems: List[str] = []
    reg = {e.cls: e for e in entries}
    for name in sorted(set(reg) - set(docs)):
        problems.append(
            f"error {name} is in the taxonomy but has no row in "
            "docs/SERVING.md 'Error taxonomy'")
    for name in sorted(set(docs) - set(reg)):
        problems.append(
            f"error {name} is documented but not in the taxonomy "
            "(analysis/errflow/taxonomy.py)")
    for name in sorted(set(docs) & set(reg)):
        e = reg[name]
        status_cell, code, retry = docs[name]
        want = (e.status_doc, e.code, e.retryable)
        if (status_cell, code, retry) != want:
            problems.append(
                f"contract drift for {name}: docs say "
                f"status={status_cell} code={code or '—'} "
                f"retryable={'yes' if retry else 'no'}, taxonomy has "
                f"status={e.status_doc} code={e.code or '—'} "
                f"retryable={'yes' if e.retryable else 'no'}")
    for e in entries:
        if not e.is_pseudo and e.cls not in known_classes:
            problems.append(
                f"taxonomy names error class {e.cls} but no such class "
                "exists in the project")
        if e.code and e.code not in codes_emitted:
            problems.append(
                f"taxonomy code '{e.code}' ({e.cls}) is never emitted "
                "in the serving tier")
        if e.status is not None and e.status not in statuses_emitted:
            problems.append(
                f"taxonomy status {e.status} ({e.cls}) never appears at "
                "a _json() response site")
    reg_codes = {e.code for e in entries if e.code}
    for code in sorted(codes_emitted - reg_codes):
        problems.append(
            f"serving tier emits code='{code}' but the taxonomy has no "
            "entry for it")
    return problems


# ---- emit-site scanning -----------------------------------------------------

def emitted_codes(trees: Dict[str, ast.Module]) -> Set[str]:
    """Every ``code`` string the serving tier can put in a response
    body: ``{"code": "x"}`` dict literals and ``body["code"] = "x"``
    subscript stores."""
    out: Set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant) and k.value == "code"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        out.add(v.value)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.slice, ast.Constant)
                            and t.slice.value == "code"
                            and isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)):
                        out.add(node.value.value)
    return out


def emitted_statuses(trees: Dict[str, ast.Module]) -> Set[int]:
    """First-argument int literals of ``._json(...)`` calls — the
    response-emit sites the span/event catalog lints' emit legs
    correspond to."""
    out: Set[int] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_json"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, int)):
                out.add(node.args[0].value)
    return out
