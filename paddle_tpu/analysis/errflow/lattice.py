"""The exception-type lattice: classification + catch semantics.

Exception types flow through the summaries engine as plain NAMES (the
final dotted component — ``QueueFull``, ``_WorkerBusy``, ``OSError``),
resolved against two hierarchies:

- the **project hierarchy** from the thread model's class index
  (``ClassInfo.bases``, resolved dotted strings), so ``except
  RuntimeError`` is known to catch ``HandoffCorrupt``;
- a **builtin hierarchy** table (the exception subtree of the stdlib
  that serving code actually meets), so ``except OSError`` is known to
  catch ``ConnectionResetError``.

Every type lands in one of four classes:

- ``control`` — a leading-underscore project exception: routing
  control flow (``_Migrated``, ``_WorkerBusy``, ``_DeadlineExpired``).
  Swallowing one breaks the router's protocol, silently.
- ``fault``   — any other project exception (``QueueFull``,
  ``HandoffCorrupt``, ``XlaOom``): a typed error with an HTTP contract.
- ``fatal``   — ``SystemExit``/``KeyboardInterrupt``/``GeneratorExit``/
  ``MemoryError``: escaping a thread root is the *intended* behavior
  (crash loud), so escape rules skip them.
- ``generic`` — everything else, including the ``Exception`` token the
  engine manufactures for calls it cannot resolve. Caught only by
  broad handlers; never reported by the typed rules.

Pure functions over the ``ProjectModel`` — no paddle_tpu import — so
fixture snippets unit-test the lattice in isolation
(tests/test_errflow_analysis.py).
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

__all__ = ["ErrorLattice", "BUILTIN_PARENT", "FATAL_TYPES",
           "CONTROL", "FAULT", "FATAL", "GENERIC", "GENERIC_TOKEN",
           "handler_spec"]

CONTROL = "control"
FAULT = "fault"
FATAL = "fatal"
GENERIC = "generic"

#: the token the engine emits for a call it cannot resolve — "external
#: code may raise something"; caught only by broad handlers
GENERIC_TOKEN = "Exception"

FATAL_TYPES = frozenset({
    "SystemExit", "KeyboardInterrupt", "GeneratorExit", "MemoryError",
})

# child -> parent, the stdlib exception subtree serving code meets.
# Aliases (IOError, EnvironmentError, socket.timeout) map onto their
# canonical node so ``except OSError`` catches all spellings.
BUILTIN_PARENT = {
    "BaseException": None,
    "SystemExit": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "GeneratorExit": "BaseException",
    "Exception": "BaseException",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "AssertionError": "Exception",
    "AttributeError": "Exception",
    "BufferError": "Exception",
    "EOFError": "Exception",
    "ImportError": "Exception",
    "ModuleNotFoundError": "ImportError",
    "LookupError": "Exception",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "MemoryError": "Exception",
    "NameError": "Exception",
    "UnboundLocalError": "NameError",
    "OSError": "Exception",
    "IOError": "OSError",
    "EnvironmentError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "PermissionError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "InterruptedError": "OSError",
    "BlockingIOError": "OSError",
    "ChildProcessError": "OSError",
    "ProcessLookupError": "OSError",
    "ConnectionError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "TimeoutError": "OSError",
    "timeout": "TimeoutError",          # socket.timeout
    "ReferenceError": "Exception",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "StopIteration": "Exception",
    "StopAsyncIteration": "Exception",
    "SyntaxError": "Exception",
    "IndentationError": "SyntaxError",
    "TabError": "IndentationError",
    "SystemError": "Exception",
    "TypeError": "Exception",
    "ValueError": "Exception",
    "UnicodeError": "ValueError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
}

_BROAD = {"Exception", "BaseException"}


def handler_spec(type_node: Optional[ast.AST],
                 resolver) -> Tuple[List[str], bool]:
    """``(type names, is_broad)`` for one ``except`` clause. A bare
    ``except`` or any ``Exception``/``BaseException`` member (alone or
    in a tuple) makes the handler broad; names resolve through the
    module's import aliases (``requests.Timeout`` -> ``Timeout``)."""
    if type_node is None:
        return [], True
    if isinstance(type_node, ast.Tuple):
        names, broad = [], False
        for elt in type_node.elts:
            n, b = handler_spec(elt, resolver)
            names.extend(n)
            broad = broad or b
        return names, broad
    dotted = resolver(type_node) if resolver is not None else ""
    name = dotted.rsplit(".", 1)[-1] if dotted else ""
    if not name:
        if isinstance(type_node, ast.Attribute):
            name = type_node.attr
        elif isinstance(type_node, ast.Name):
            name = type_node.id
    if name in _BROAD:
        return [name], True
    return ([name] if name else []), False


class ErrorLattice:
    """Classification and subtype queries over one ``ProjectModel``."""

    def __init__(self, model):
        self.model = model
        self._ancestors_cache = {}
        self._class_cache = {}

    # ---- hierarchy -------------------------------------------------------
    def is_project_exception(self, name: str) -> bool:
        """True when ``name`` is a project class whose base chain
        reaches the builtin exception tree."""
        hit = self._class_cache.get(name)
        if hit is not None:
            return hit
        out = False
        for cls in self.model.classes_by_name.get(name, ()):
            for c in self.model.mro(cls):
                for base in c.bases:
                    if base.rsplit(".", 1)[-1] in BUILTIN_PARENT:
                        out = True
        self._class_cache[name] = out
        return out

    def ancestors(self, name: str) -> Set[str]:
        """``name`` plus every ancestor type name, through project bases
        into the builtin tree (cycle-safe; union over same-named project
        classes)."""
        hit = self._ancestors_cache.get(name)
        if hit is not None:
            return hit
        out: Set[str] = set()
        stack = [name]
        while stack:
            n = stack.pop()
            if n in out:
                continue
            out.add(n)
            parent = BUILTIN_PARENT.get(n)
            if parent:
                stack.append(parent)
            for cls in self.model.classes_by_name.get(n, ()):
                stack.extend(b.rsplit(".", 1)[-1] for b in cls.bases)
        self._ancestors_cache[name] = out
        return out

    # ---- classification --------------------------------------------------
    def classify(self, name: str) -> str:
        if name in FATAL_TYPES:
            return FATAL
        if self.is_project_exception(name):
            return CONTROL if name.startswith("_") else FAULT
        return GENERIC

    # ---- catch semantics -------------------------------------------------
    def caught_by(self, exc_name: str, handler_names: Iterable[str],
                  broad: bool = False) -> bool:
        """Does ``except (handler_names)`` stop ``exc_name``? True when
        any handler name is ``exc_name`` or one of its ancestors. The
        ``GENERIC_TOKEN`` (an *unknown* external exception) is caught
        only by broad handlers — a narrow ``except ValueError`` may or
        may not match it, and escape analysis must stay conservative."""
        if broad:
            return True
        if exc_name == GENERIC_TOKEN:
            return False
        anc = self.ancestors(exc_name)
        return any(h in anc for h in handler_names)
