"""Interprocedural exception summaries: what can escape each function.

Composes the two analyses that already exist:

- the **PR-18 CFG** (``analysis/cfg.py``) supplies handler-dispatch
  structure per function — ``except`` dispatch nodes chaining handlers
  in order with ``nomatch`` edges, ``finally`` bodies duplicated per
  continuation kind, and a ``raise`` exit;
- the **PR-9 thread model** (``analysis/threads/model.py``) supplies
  the whole-program call graph (``edges``, ``call_targets``) and the
  class index the lattice resolves types against.

Per function, a forward dataflow over the CFG's exceptional edges
computes the set of exception TYPES (lattice names, with raise-site
provenance) that can reach each handler dispatch and the ``raise``
exit:

- an explicit ``raise X(...)`` contributes ``X``;
- a bare ``raise`` inside a handler re-raises that handler's arrival
  set (so a narrow-then-re-raise handler is transparent);
- ``raise e`` where ``e`` is the handler's bound name does the same;
- any other statement containing calls contributes the union of its
  resolved project callees' summaries, or the ``GENERIC_TOKEN``
  (``Exception``) for calls the model cannot resolve — minus the
  lifecycle ``NORAISE`` allowlist (loggers, clocks, metric counters);
- at an ``except`` dispatch, each handler subtracts the types it
  catches (lattice subtype query; broad handlers catch everything) and
  passes the remainder down the ``nomatch`` chain and out the final
  ``raise`` edge;
- a ``finally`` raise-copy passes the in-flight set through to the
  outer raise target (its own statements contribute their own raises).

Function summaries reach a fixpoint over the call graph with a
worklist — SCCs (mutual recursion) converge because the per-type sets
only grow. The per-function pass itself iterates until handler-arrival
sets stabilize (a bare ``raise`` feeds on them).

``ErrorFlow`` is the cached engine the ``--errors`` rules share; one
instance per ``ProjectModel`` (``get_flow``), so ``--threads --errors``
builds the model once and ``--errors`` reuses every parsed tree.
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from ..cfg import _eager_nodes, build_cfg
from ..lifecycle.resources import NORAISE
from .lattice import ErrorLattice, GENERIC_TOKEN, handler_spec

__all__ = ["ErrorFlow", "get_flow", "Summary", "NORAISE_ERRFLOW"]

# Stop-event plumbing on top of the lifecycle allowlist: a daemon
# loop's own head (``while not self._stop.wait(t)``, ``while not
# self._stop.is_set()``) and plain sleeps never raise non-fatally —
# without these every correctly guarded root would still "escape"
# through its loop condition. ``is_set`` is bare (only event-likes
# have it); ``wait`` is full-path only (``proc.wait(timeout=)`` DOES
# raise).
NORAISE_ERRFLOW = NORAISE | frozenset({
    "self._stop.wait", "self._stop.is_set", "self._stop.clear",
    "stop.wait", "stop.is_set", "done.wait", "is_set", "time.sleep",
    # ``Popen.poll`` and one-argument ``type(e)`` in log lines cannot
    # fail; teardown ``close()`` in a finally is no-raise by the same
    # convention that puts it on the lifecycle release path
    "poll", "type", "close",
    # pure state resets (backoff ladders, breakers) by the same
    # convention as the builtin ``clear``/``update`` entries
    "reset",
    # the stdlib client constructor stores fields — connect is lazy,
    # on request()
    "http.client.HTTPConnection",
})

#: escaping type name -> (rel_file, line) of the first-seen raise site
Summary = Dict[str, Tuple[str, int]]

FuncKey = Tuple[str, str]


class ErrorFlow:
    """The summaries engine over one ``ProjectModel``."""

    def __init__(self, model, noraise=NORAISE_ERRFLOW):
        self.model = model
        self.lattice = ErrorLattice(model)
        self.noraise = frozenset(noraise)
        #: FuncKey -> Summary (escaping set), for every analyzed function
        self.summaries: Dict[FuncKey, Summary] = {}
        #: id(ast.ExceptHandler) -> Summary arriving at that handler
        #: (the caught set), for every analyzed function — what the
        #: swallow and retry rules read
        self.handler_arrivals: Dict[int, Summary] = {}
        self._cfgs: Dict[FuncKey, object] = {}
        self._encl_handler: Dict[FuncKey, Dict[int, ast.ExceptHandler]] = {}
        self._analyzed: Set[FuncKey] = set()

    # ---- public API ------------------------------------------------------
    def escapes_of(self, key: FuncKey) -> Summary:
        """The escape summary for one function (analyzing on demand)."""
        self.analyze([key])
        return self.summaries.get(key, {})

    def typed(self, summary: Summary, classes=("control", "fault")
              ) -> Summary:
        """The control/fault subset of a summary — what the typed rules
        report (generic externals and fatal signals are noise)."""
        return {t: o for t, o in summary.items()
                if self.lattice.classify(t) in classes}

    def analyze(self, roots: List[FuncKey]):
        """Fixpoint the summaries for ``roots`` and everything they
        reach through the call graph. Idempotent per key."""
        todo = [k for k in roots
                if k in self.model.functions and k not in self._analyzed]
        if not todo:
            return
        reach: Set[FuncKey] = set()
        stack = list(todo)
        while stack:
            k = stack.pop()
            if k in reach or k not in self.model.functions:
                continue
            reach.add(k)
            for (callee, _line) in self.model.edges.get(k, ()):
                stack.append(callee)
        callers: Dict[FuncKey, Set[FuncKey]] = {}
        for k in reach:
            for (callee, _line) in self.model.edges.get(k, ()):
                if callee in reach:
                    callers.setdefault(callee, set()).add(k)
        work = deque(sorted(reach))
        queued = set(work)
        while work:
            k = work.popleft()
            queued.discard(k)
            new = self._evaluate(k)
            if new != self.summaries.get(k):
                self.summaries[k] = new
                for caller in callers.get(k, ()):
                    if caller not in queued:
                        queued.add(caller)
                        work.append(caller)
        self._analyzed |= reach

    # ---- per-function evaluation -----------------------------------------
    def function_cfg(self, key: FuncKey):
        cfg = self._cfgs.get(key)
        if cfg is None:
            fn = self.model.functions[key]
            ctx = self.model.modules[fn.file].ctx
            cfg = build_cfg(fn.node, resolver=ctx.resolve_call,
                            noraise=self.noraise)
            self._cfgs[key] = cfg
            # innermost enclosing handler per raise statement, for bare
            # ``raise`` / ``raise e`` re-raise semantics
            encl: Dict[int, ast.ExceptHandler] = {}

            def walk(node, handler):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue       # nested scope: its own CFG
                    h = child if isinstance(child, ast.ExceptHandler) \
                        else handler
                    if isinstance(child, ast.Raise) and h is not None:
                        encl[id(child)] = h
                    walk(child, h)

            walk(fn.node, None)
            self._encl_handler[key] = encl
        return cfg

    def _evaluate(self, key: FuncKey) -> Summary:
        fn = self.model.functions[key]
        ctx = self.model.modules[fn.file].ctx
        cfg = self.function_cfg(key)
        arrivals: Dict[int, Summary] = {}
        while True:
            escapes, new_arr = self._propagate(key, fn, ctx, cfg, arrivals)
            if new_arr == arrivals:
                break
            arrivals = new_arr
        for hid, s in arrivals.items():
            merged = dict(self.handler_arrivals.get(hid, {}))
            merged.update({t: o for t, o in s.items() if t not in merged})
            self.handler_arrivals[hid] = merged
        return escapes

    def _propagate(self, key, fn, ctx, cfg, arr_in):
        """One forward pass over the exceptional edges: returns (escape
        summary, handler arrivals). ``arr_in`` feeds bare-raise gen."""
        pending: Dict[int, Summary] = {}
        arrivals: Dict[int, Summary] = {}
        work: deque = deque()
        queued: Set[int] = set()

        def contribute(nid: int, items: Summary):
            if not items:
                return
            tgt = pending.setdefault(nid, {})
            new = {t: o for t, o in items.items() if t not in tgt}
            if not new:
                return
            tgt.update(new)
            if (cfg.nodes[nid].kind in ("except", "finally")
                    and nid not in queued):
                queued.add(nid)
                work.append(nid)

        for nid in sorted(cfg.nodes):
            g = self._gen(key, fn, ctx, cfg.nodes[nid], arr_in)
            if not g:
                continue
            for (dst, kind) in cfg.succ(nid):
                if kind == "raise":
                    contribute(dst, g)

        while work:
            nid = work.popleft()
            queued.discard(nid)
            node = cfg.nodes[nid]
            items = dict(pending.get(nid, {}))
            if node.kind == "except":
                self._dispatch(cfg, nid, items, arrivals, contribute, ctx)
            elif node.kind == "finally":
                self._passthrough(cfg, nid, items, contribute)
        return dict(pending.get(cfg.raise_exit, {})), arrivals

    def _dispatch(self, cfg, nid, items, arrivals, contribute, ctx):
        """Walk the handler chain off one dispatch node: each handler
        subtracts what it catches; the remainder leaves on the last
        handler's ``raise`` edge (absent when a broad handler ends the
        chain)."""
        remaining = dict(items)
        cur = nid
        while True:
            nxt = [d for (d, k) in cfg.succ(cur)
                   if k in ("except", "nomatch")
                   and cfg.nodes[d].kind == "handler"]
            if not nxt:
                break
            hnode = cfg.nodes[nxt[0]]
            hstmt = hnode.stmt                    # ast.ExceptHandler
            names, broad = handler_spec(hstmt.type, ctx.resolve_call)
            caught = {t: o for t, o in remaining.items()
                      if self.lattice.caught_by(t, names, broad)}
            tgt = arrivals.setdefault(id(hstmt), {})
            tgt.update({t: o for t, o in caught.items() if t not in tgt})
            remaining = {t: o for t, o in remaining.items()
                         if t not in caught}
            cur = hnode.id
        if remaining:
            for (d, k) in cfg.succ(cur):
                if k == "raise":
                    contribute(d, remaining)

    def _passthrough(self, cfg, nid, items, contribute):
        """A ``finally`` raise-copy: the in-flight set survives the
        finally body (unless the body raises its own — those edges get
        their own gen contributions) and leaves on every ``raise`` edge
        out of the copy. Slight over-approximation: a finally that
        raises masks the pending exception, we keep both."""
        seen = {nid}
        stack = [nid]
        while stack:
            n = stack.pop()
            for (d, k) in cfg.succ(n):
                if k == "raise":
                    contribute(d, items)
                elif d not in seen:
                    seen.add(d)
                    stack.append(d)

    # ---- gen sets --------------------------------------------------------
    def _gen(self, key, fn, ctx, node, arr_in) -> Summary:
        """What executing ``node`` can itself raise (callee summaries
        included), independent of anything already in flight."""
        s = node.stmt
        if s is None or node.kind in ("except", "handler", "finally",
                                      "loopexit"):
            return {}
        if node.kind == "stmt" and isinstance(s, ast.Raise):
            return self._gen_raise(key, fn, ctx, s, arr_in)
        if node.kind == "stmt" and isinstance(s, ast.Assert):
            return {"AssertionError": (fn.file, s.lineno)}
        if node.kind == "branch":
            roots = [s.test]
        elif node.kind == "loop":
            roots = [s.iter] if isinstance(s, (ast.For, ast.AsyncFor)) \
                else [s.test]
        elif node.kind == "with":
            roots = [item.context_expr for item in s.items]
        else:
            roots = [s]
        out: Summary = {}
        for root in roots:
            for sub in _eager_nodes(root):
                if isinstance(sub, ast.Await):
                    out.setdefault(GENERIC_TOKEN,
                                   (fn.file, getattr(sub, "lineno",
                                                     s.lineno)))
                elif isinstance(sub, ast.Call):
                    self._gen_call(fn, ctx, sub, out)
        return out

    def _gen_call(self, fn, ctx, call, out: Summary):
        # an exact full-path allowlist entry (``self._stop.wait``,
        # ``done.wait``) is a no-raise CONTRACT on that call site — it
        # beats target resolution, which can mis-bind an Event method
        # to a same-named project function
        resolved = ctx.resolve_call(call.func)
        if resolved and resolved in self.noraise:
            return
        targets = self.model.call_targets.get(id(call))
        if targets:
            for t in targets:
                # a resolved TOP-LEVEL function whose name is on the
                # allowlist keeps its no-raise contract (get_logger);
                # methods have dotted qualnames so ``ShmChannel.get``
                # is never masked by the bare builtin entry ``get``
                if t[1] in self.noraise:
                    continue
                for typ, origin in self.summaries.get(t, {}).items():
                    out.setdefault(typ, origin)
            return
        if resolved and resolved.rsplit(".", 1)[-1] in self.noraise:
            return
        # chains rooted at a call (``get_logger().warning(...)``) defeat
        # dotted-path resolution; the method name alone still settles
        # the noraise question
        if (not resolved and isinstance(call.func, ast.Attribute)
                and call.func.attr in self.noraise):
            return
        out.setdefault(GENERIC_TOKEN, (fn.file, call.lineno))

    def _gen_raise(self, key, fn, ctx, s: ast.Raise, arr_in) -> Summary:
        handler = self._encl_handler.get(key, {}).get(id(s))
        if s.exc is None:
            # bare re-raise: the enclosing handler's arrival set
            if handler is not None:
                return dict(arr_in.get(id(handler), {}))
            return {GENERIC_TOKEN: (fn.file, s.lineno)}
        if (handler is not None and handler.name
                and isinstance(s.exc, ast.Name)
                and s.exc.id == handler.name):
            # ``except X as e: ... raise e`` — same as a bare raise
            return dict(arr_in.get(id(handler), {}))
        target = s.exc.func if isinstance(s.exc, ast.Call) else s.exc
        dotted = ctx.resolve_call(target)
        name = dotted.rsplit(".", 1)[-1] if dotted else ""
        # CamelCase (after any leading underscores — control-plane types
        # are ``_Migrated``-style by convention) means a class reference;
        # anything else is ``raise some_variable`` with the type unknown
        if not name.lstrip("_")[:1].isupper():
            name = GENERIC_TOKEN
        return {name: (fn.file, s.lineno)}


# one engine per model: --errors rules share summaries, and a combined
# --threads --errors run reuses the model get_model() already built
_FLOWS: Dict[int, ErrorFlow] = {}


def get_flow(model) -> ErrorFlow:
    flow = _FLOWS.get(id(model))
    if flow is None or flow.model is not model:
        _FLOWS.clear()
        flow = ErrorFlow(model)
        _FLOWS[id(model)] = flow
    return flow
