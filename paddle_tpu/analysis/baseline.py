"""Baseline: checked-in grandfathered findings (``.pdlint_baseline.json``).

A new rule landing on an old codebase faces a choice: fix every historic
finding in the same PR, or never land the rule. The baseline is the
third option — existing findings are recorded once and stop failing the
gate, while any NEW finding (a key not in the file) still fails. Entries
key on ``(file, rule, symbol, message)`` — no line numbers — so edits
elsewhere in a file don't churn the baseline; moving or renaming the
enclosing function intentionally invalidates the entry (the code changed,
the finding deserves a fresh look).

The file is a plain sorted-JSON list so diffs review like code.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Set, Tuple

from .core import Finding, module_context

__all__ = ["load", "save", "save_entries", "filter_new", "to_entries",
           "load_entries", "stale_entries"]

_VERSION = 1
_FIELDS = ("file", "rule", "symbol", "message")

Key = Tuple[str, str, str, str]


def to_entries(findings: Iterable[Finding]) -> List[Dict[str, str]]:
    entries = [{"file": f.file, "rule": f.rule, "symbol": f.symbol,
                "message": f.message} for f in findings]
    seen: Set[Key] = set()
    out = []
    for e in sorted(entries, key=lambda d: tuple(d[k] for k in _FIELDS)):
        k = tuple(e[f] for f in _FIELDS)
        if k not in seen:
            seen.add(k)
            out.append(e)
    return out


def save(path: str, findings: Iterable[Finding]) -> int:
    return save_entries(path, to_entries(findings))


def save_entries(path: str, entries: List[Dict[str, str]]) -> int:
    """Write raw entry dicts (what ``--prune-baseline`` rewrites after
    dropping stale ones — no lint run involved)."""
    entries = sorted(entries, key=lambda d: tuple(d[k] for k in _FIELDS))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": _VERSION, "findings": entries}, fh, indent=1,
                  sort_keys=True)
        fh.write("\n")
    return len(entries)


def load(path: str) -> Set[Key]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != _VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return {tuple(e[f] for f in _FIELDS) for e in data["findings"]}


def filter_new(findings: Iterable[Finding],
               baseline: Set[Key]) -> List[Finding]:
    """Findings whose key is NOT grandfathered (the ones that fail)."""
    return [f for f in findings if f.key() not in baseline]


def load_entries(path: str) -> List[Dict[str, str]]:
    """The raw entry dicts (``load`` collapses to keys; pruning needs
    the fields)."""
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != _VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return list(data["findings"])


def _symbols_in(path: str, rel: str) -> Set[str]:
    """Every def/class qualname a file defines (the ``symbol`` namespace
    findings key on), plus "" for module level. Goes through the shared
    parse cache — a stale check right after a lint run re-parses
    nothing."""
    return module_context(path, rel).symbols()


def stale_entries(entries: Iterable[Dict[str, str]],
                  root: str) -> List[Dict[str, str]]:
    """Entries whose (file, symbol) no longer resolves: the file is gone,
    unparsable, or no longer defines the symbol — dead weight that would
    otherwise linger in the baseline forever. Graph-finding entries
    (``<graph:...>``/``<preflight:...>`` pseudo-files) are never stale on
    this test; they key on model+eqn, not source symbols."""
    cache: Dict[str, Set[str]] = {}
    out: List[Dict[str, str]] = []
    for e in entries:
        rel = e.get("file", "")
        if rel.startswith("<"):
            continue
        path = os.path.join(root, rel)
        if rel not in cache:
            try:
                cache[rel] = _symbols_in(path, rel)
            except (OSError, SyntaxError):
                cache[rel] = set()   # gone or unparsable: all stale
        if e.get("symbol", "") not in cache[rel]:
            out.append(e)
    return out
