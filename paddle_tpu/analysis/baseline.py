"""Baseline: checked-in grandfathered findings (``.pdlint_baseline.json``).

A new rule landing on an old codebase faces a choice: fix every historic
finding in the same PR, or never land the rule. The baseline is the
third option — existing findings are recorded once and stop failing the
gate, while any NEW finding (a key not in the file) still fails. Entries
key on ``(file, rule, symbol, message)`` — no line numbers — so edits
elsewhere in a file don't churn the baseline; moving or renaming the
enclosing function intentionally invalidates the entry (the code changed,
the finding deserves a fresh look).

The file is a plain sorted-JSON list so diffs review like code.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Set, Tuple

from .core import Finding

__all__ = ["load", "save", "filter_new", "to_entries"]

_VERSION = 1
_FIELDS = ("file", "rule", "symbol", "message")

Key = Tuple[str, str, str, str]


def to_entries(findings: Iterable[Finding]) -> List[Dict[str, str]]:
    entries = [{"file": f.file, "rule": f.rule, "symbol": f.symbol,
                "message": f.message} for f in findings]
    seen: Set[Key] = set()
    out = []
    for e in sorted(entries, key=lambda d: tuple(d[k] for k in _FIELDS)):
        k = tuple(e[f] for f in _FIELDS)
        if k not in seen:
            seen.add(k)
            out.append(e)
    return out


def save(path: str, findings: Iterable[Finding]) -> int:
    entries = to_entries(findings)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": _VERSION, "findings": entries}, fh, indent=1,
                  sort_keys=True)
        fh.write("\n")
    return len(entries)


def load(path: str) -> Set[Key]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != _VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return {tuple(e[f] for f in _FIELDS) for e in data["findings"]}


def filter_new(findings: Iterable[Finding],
               baseline: Set[Key]) -> List[Finding]:
    """Findings whose key is NOT grandfathered (the ones that fail)."""
    return [f for f in findings if f.key() not in baseline]
