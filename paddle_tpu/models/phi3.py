"""Phi-3 decoder family (mini / medium, 4k and 128k variants).

Architecturally the Llama recipe (RoPE GQA, SwiGLU, RMSNorm, untied head)
— the deviations are checkpoint packaging and long-context scaling:

- fused projections in the checkpoint: ``qkv_proj`` ([q; k; v] stacked on
  the out dim) and ``gate_up_proj`` ([gate; up]) — split here at CONVERT
  time so the runtime keeps the trunk's separate (column-parallel)
  projections;
- LongRoPE (``rope_scaling type "longrope"``) for the 128k variants:
  per-dim short/long frequency factor lists chosen by the table length
  against ``original_max_position_embeddings``, with the
  sqrt(1 + ln(f)/ln(orig)) magnitude factor (llama._longrope_params);
- optional causal sliding window (the mini-4k ships 2047) on the trunk's
  uniform-window machinery;
- partial rotary (the small variants' partial_rotary_factor) via the
  trunk's width-keyed rope tables.
"""
from __future__ import annotations

import dataclasses

from .llama import (LlamaConfig, LlamaForCausalLM, _from_hf, _hf_get,
                    _hf_to_np)


@dataclasses.dataclass
class Phi3Config(LlamaConfig):
    # Phi-3-mini shape
    vocab_size: int = 32064
    hidden_size: int = 3072
    intermediate_size: int = 8192
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32")
        base.update(kw)
        return Phi3Config(**base)


class Phi3ForCausalLM(LlamaForCausalLM):
    """Phi-3 causal LM — the Llama trunk; the family identity lives in the
    checkpoint converter (fused-projection split + LongRoPE mapping)."""


def split_phi3_fused(hf_state_dict, hf_config):
    """Translate a transformers Phi3 state dict to the Llama key layout:
    ``qkv_proj`` splits into q/k/v on the out dim (torch [out, in] rows),
    ``gate_up_proj`` into equal gate/up halves. Returns a new dict; all
    other keys pass through unchanged."""
    get = _hf_get(hf_config)
    h = get("hidden_size")
    heads = get("num_attention_heads")
    kv = get("num_key_value_heads")
    hd = get("head_dim") or h // heads
    out = {}
    for key, val in hf_state_dict.items():
        if key.endswith(".self_attn.qkv_proj.weight"):
            base = key[: -len("qkv_proj.weight")]
            v = _hf_to_np(val)
            if v.shape[0] != (heads + 2 * kv) * hd:
                raise ValueError(
                    f"{key}: fused qkv rows {v.shape[0]} != "
                    f"(H + 2*kv) * head_dim = {(heads + 2 * kv) * hd}")
            out[base + "q_proj.weight"] = v[: heads * hd]
            out[base + "k_proj.weight"] = v[heads * hd: (heads + kv) * hd]
            out[base + "v_proj.weight"] = v[(heads + kv) * hd:]
        elif key.endswith(".mlp.gate_up_proj.weight"):
            split_gate_up(key, _hf_to_np(val), out)
        else:
            out[key] = val
    return out


def split_gate_up(key, v, out):
    """Fused [gate; up] checkpoint rows -> separate gate_proj/up_proj
    entries (torch [out, in] halves) — shared by the phi3 and glm
    translators."""
    base = key[: -len("gate_up_proj.weight")]
    half = v.shape[0] // 2
    out[base + "gate_proj.weight"] = v[:half]
    out[base + "up_proj.weight"] = v[half:]


def phi3_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a Phi3ForCausalLM from a transformers Phi3 model (or a raw
    state dict + config)."""
    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    get = _hf_get(hf_config)
    scaling = get("rope_scaling")
    if scaling:
        # the factor-list choice anchors to original_max_position_embeddings,
        # which Phi3 keeps as a CONFIG attribute — fold it into the scaling
        # dict so the table builder sees it
        scaling = dict(scaling)
        orig = get("original_max_position_embeddings")
        if orig:
            scaling.setdefault("original_max_position_embeddings", orig)
        config_overrides.setdefault("rope_scaling", scaling)
    # the base mapper's window logic is mistral-keyed; Phi3's window (the
    # mini-4k ships 2047) maps directly
    config_overrides.setdefault("sliding_window", get("sliding_window"))
    return _from_hf(Phi3Config, Phi3ForCausalLM,
                    split_phi3_fused(state, hf_config), hf_config,
                    **config_overrides)
