"""Gemma decoder family (Gemma-2B / 7B).

Role parity: the reference's decoder zoo trains pre-norm RoPE
architectures on the fleet hybrid stack (SURVEY §2.7 CS4); Gemma is that
recipe with three signature deviations, each a LlamaConfig knob so the
whole machinery (training, hybrid parallel, caches, serving, beam, LoRA)
is the already-tested Llama path:

- ``hidden_act="gelu_pytorch_tanh"``: GeGLU MLP (tanh-gelu gate instead of
  silu);
- ``rms_norm_offset=True``: norm weight parameterized as (1 + w), w
  zeros-init — the checkpoint stores the delta from identity;
- ``scale_embeddings=True``: embedding output multiplied by
  sqrt(hidden_size) (the normalizer rounds to the compute dtype first).

Plus head_dim 256 decoupled from hidden/heads (the Qwen3 knob) and tied
embeddings always. ``gemma_from_hf`` converts transformers checkpoints —
the key layout is exactly Llama's, so the mechanical loader is shared.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import LlamaConfig, LlamaForCausalLM, _from_hf, _hf_get


@dataclasses.dataclass
class GemmaConfig(LlamaConfig):
    # Gemma-7B shape
    vocab_size: int = 256000
    hidden_size: int = 3072
    intermediate_size: int = 24576
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    head_dim: Optional[int] = 256
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = True
    hidden_act: str = "gelu_pytorch_tanh"
    rms_norm_offset: bool = True
    scale_embeddings: bool = True

    @staticmethod
    def gemma_2b(**kw):
        # 2B is the MQA member: 8 heads over 1 kv head, head_dim 256
        base = dict(hidden_size=2048, intermediate_size=16384,
                    num_hidden_layers=18, num_attention_heads=8,
                    num_key_value_heads=1)
        base.update(kw)
        return GemmaConfig(**base)

    @staticmethod
    def tiny(**kw):
        # head_dim 32 != hidden/heads (16): the decoupling stays exercised
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, head_dim=32,
                    max_position_embeddings=256, dtype="float32")
        base.update(kw)
        return GemmaConfig(**base)


class GemmaForCausalLM(LlamaForCausalLM):
    """Gemma causal LM — Llama decoder with GeGLU, (1+w) norms, scaled
    embeddings, and a tied head."""

    def __init__(self, config: GemmaConfig):
        if config.hidden_act != "gelu_pytorch_tanh":
            raise ValueError("Gemma uses hidden_act='gelu_pytorch_tanh'")
        if not config.rms_norm_offset:
            raise ValueError("Gemma norms are (1 + w)-parameterized "
                             "(rms_norm_offset=True)")
        if not config.scale_embeddings:
            raise ValueError("Gemma scales embeddings by sqrt(hidden_size) "
                             "(scale_embeddings=True)")
        if not config.tie_word_embeddings:
            raise ValueError("Gemma ties the lm head to the embedding")
        super().__init__(config)


def gemma_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a GemmaForCausalLM from a transformers Gemma model (or a raw
    state dict + config)."""
    src = hf_config if hf_config is not None else hf_model_or_state.config
    get = _hf_get(src)
    # HF Gemma carries the real activation in hidden_activation (modeling
    # falls back to gelu_pytorch_tanh when unset); hidden_act in those
    # configs is vestigial
    config_overrides.setdefault(
        "hidden_act", get("hidden_activation") or "gelu_pytorch_tanh")
    config_overrides.setdefault("rms_norm_offset", True)
    config_overrides.setdefault("scale_embeddings", True)
    return _from_hf(GemmaConfig, GemmaForCausalLM, hf_model_or_state,
                    hf_config, **config_overrides)
