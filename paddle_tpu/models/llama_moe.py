"""MoE causal LM — the DeepSeekMoE / Qwen2-MoE decoder family.

Reference anchors: the fused MoE machinery the reference serves these models
with (paddle/phi/kernels/fusion/cutlass/fused_moe_kernel.cu, the
moe_gate_dispatch SPMD rule paddle/phi/infermeta/spmd_rules/moe_gate_dispatch.cc,
python/paddle/incubate/distributed/models/moe/moe_layer.py:263) and the
DeepSeekMoE/Qwen2-MoE configs named in BASELINE.json.

Architecture (DeepSeekMoE): a Llama-style decoder where every layer past
``first_k_dense_replace`` swaps the dense SwiGLU MLP for
- ``n_routed_experts`` fine-grained routed experts (top-k, softmax-normalized
  combine weights) implemented as a GroupedMLP (grouped GEMM, EP-shardable), plus
- ``n_shared_experts`` always-on shared experts (one fused SwiGLU).

TPU-native: routing/dispatch runs as one pure stage (dense GShard dispatch
einsums — MXU-friendly, GSPMD-shardable over the ep axis); the attention
block and norms are reused from models/llama.py unchanged.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from .. import nn
from ..nn.initializer import Constant, Normal, XavierUniform
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap
from .llama import (LlamaAttention, LlamaConfig, LlamaMLP, LlamaRMSNorm,
                    LlamaModel, LlamaForCausalLM)


@dataclasses.dataclass
class LlamaMoEConfig(LlamaConfig):
    """DeepSeekMoE/Qwen2-MoE knobs on top of the Llama base."""

    n_routed_experts: int = 8
    n_shared_experts: int = 1
    shared_expert_gate: bool = False       # Qwen2-MoE sigmoid shared gate
    moe_correction_bias: bool = False      # ERNIE/DeepSeek-V3 aux-free
    # balancing: a per-expert bias added to the router probs for top-k
    # SELECTION only (combine weights stay the raw softmax probs)
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 1408      # per-expert FFN width
    first_k_dense_replace: int = 1         # leading dense layers (DeepSeek)
    norm_topk_prob: bool = True            # Qwen2-MoE renormalizes top-k
    router_aux_loss_coef: float = 0.001
    moe_capacity_factor: float = 2.0
    # DeepSeek-V3 routing: sigmoid affinity scores (softmax is V2/Qwen2),
    # and a scalar multiplier on the routed-experts output
    moe_scoring_func: str = "softmax"
    routed_scaling_factor: float = 1.0
    # group-limited (device-limited) routing: experts split into n_group
    # groups, top-k restricted to the best topk_group groups per token
    # (DeepSeek-V2 group_limited_greedy / V3 noaux_tc)
    n_group: int = 1
    topk_group: int = 1

    @staticmethod
    def tiny_moe(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=3, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32", n_routed_experts=4,
                    num_experts_per_tok=2, moe_intermediate_size=64,
                    first_k_dense_replace=1)
        base.update(kw)
        return LlamaMoEConfig(**base)


def load_hf_grouped_moe(model, hf_state_dict, *, attn_biases=False,
                        qk_norms=False, shared_expert=False,
                        shared_gate=False, who="load_hf_moe",
                        mlp_key="mlp",
                        expert_keys=("gate_proj", "up_proj", "down_proj")):
    """Shared HF→grouped-layout loader for the Qwen-MoE family shapes:
    embed/norm/lm_head, per-layer attention (optionally q/k/v biases or
    per-head q/k norms), router, per-expert projections packed via
    pack_hf_experts, optional (gated) shared expert. torch [out, in]
    weights transpose to [in, out].

    ``mlp_key``/``expert_keys`` rename the MoE block for checkpoints that
    don't follow the Qwen layout (Mixtral: ``block_sparse_moe`` with
    per-expert ``w1``/``w3``/``w2`` as gate/up/down)."""
    from .llama import _hf_to_np

    cfg = model.config
    E, L = cfg.n_routed_experts, cfg.num_hidden_layers
    mapped, consumed = {}, set()

    def take(hf_key, transpose):
        if hf_key not in hf_state_dict:
            raise KeyError(f"{who}: missing {hf_key!r}")
        consumed.add(hf_key)
        v = _hf_to_np(hf_state_dict[hf_key])
        return v.T if transpose else v

    mapped["llama.embed_tokens.weight"] = take("model.embed_tokens.weight",
                                               False)
    mapped["llama.norm.weight"] = take("model.norm.weight", False)
    if model.lm_head is not None:
        src = ("lm_head.weight" if "lm_head.weight" in hf_state_dict
               else "model.embed_tokens.weight")
        mapped["lm_head.weight"] = take(src, True)
    for i in range(L):
        hf, ours = f"model.layers.{i}", f"llama.layers.{i}"
        for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
            mapped[f"{ours}.self_attn.{proj}.weight"] = take(
                f"{hf}.self_attn.{proj}.weight", True)
        if attn_biases:
            for proj in ("q_proj", "k_proj", "v_proj"):
                mapped[f"{ours}.self_attn.{proj}.bias"] = take(
                    f"{hf}.self_attn.{proj}.bias", False)
        if qk_norms:
            for norm in ("q_norm", "k_norm"):
                mapped[f"{ours}.self_attn.{norm}.weight"] = take(
                    f"{hf}.self_attn.{norm}.weight", False)
        mapped[f"{ours}.input_layernorm.weight"] = take(
            f"{hf}.input_layernorm.weight", False)
        mapped[f"{ours}.post_attention_layernorm.weight"] = take(
            f"{hf}.post_attention_layernorm.weight", False)
        # router: HF [E, h] -> gate_weight [h, E]
        mapped[f"{ours}.mlp.gate_weight"] = take(
            f"{hf}.{mlp_key}.gate.weight", True)
        (mapped[f"{ours}.mlp.experts.w1"],
         mapped[f"{ours}.mlp.experts.b1"],
         mapped[f"{ours}.mlp.experts.w2"],
         mapped[f"{ours}.mlp.experts.b2"]) = pack_hf_experts(
            take, f"{hf}.{mlp_key}", E, cfg.hidden_size,
            expert_keys=expert_keys)
        if shared_expert:
            for proj in ("gate_proj", "up_proj", "down_proj"):
                mapped[f"{ours}.mlp.shared_expert.{proj}.weight"] = take(
                    f"{hf}.{mlp_key}.shared_expert.{proj}.weight", True)
        if shared_gate:
            # shared gate: HF [1, h] -> [h, 1]
            mapped[f"{ours}.mlp.shared_gate_weight"] = take(
                f"{hf}.{mlp_key}.shared_expert_gate.weight", True)
    leftovers = [k for k in hf_state_dict
                 if k not in consumed and k != "lm_head.weight"
                 and not k.endswith("rotary_emb.inv_freq")]
    if leftovers:
        raise ValueError(
            f"{who}: checkpoint tensors this model cannot represent: "
            f"{leftovers[:5]}{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected
    if missing:
        raise KeyError(f"{who}: model keys not covered: {missing[:5]}")
    return model


def pack_hf_experts(take, hf_prefix, n_experts, hidden_size,
                    expert_keys=("gate_proj", "up_proj", "down_proj")):
    """Stack a transformers checkpoint's per-expert gate/up/down weights
    into the grouped [E, ...] layout (shared by the qwen2_moe, ernie45 and
    mixtral loaders): returns (w1 fused gate||up, b1 zeros, w2, b2 zeros).
    ``expert_keys`` names the (gate, up, down) projections in the HF
    checkpoint (Mixtral: w1/w3/w2)."""
    import numpy as np

    gate_k, up_k, down_k = expert_keys
    w1 = np.stack([
        np.concatenate([take(f"{hf_prefix}.experts.{e}.{gate_k}.weight",
                             True),
                        take(f"{hf_prefix}.experts.{e}.{up_k}.weight",
                             True)], axis=-1)
        for e in range(n_experts)])
    w2 = np.stack([take(f"{hf_prefix}.experts.{e}.{down_k}.weight", True)
                   for e in range(n_experts)])
    b1 = np.zeros((n_experts, 1, w1.shape[-1]), np.float32)
    b2 = np.zeros((n_experts, 1, hidden_size), np.float32)
    return w1, b1, w2, b2


class MoEMLP(Layer):
    """Routed experts + shared experts (DeepSeekMoE block).

    The routed path is the dense GShard dispatch: router → top-k → capacity
    positions → [S, E, C] combine/dispatch einsums → grouped FFN → combine.
    All of it is one pure function per call, so GSPMD shards the expert dim
    over the ep/data axes and the dispatch einsums become all_to_alls.
    """

    def __init__(self, config: LlamaMoEConfig):
        super().__init__(dtype=config.dtype)
        from ..distributed.moe import (GroupedMLP, default_ep_axes,
                                       shard_grouped_experts)
        from ..framework.dtype import dtype_guard

        self.config = config
        h = config.hidden_size
        self.gate_weight = self.create_parameter(
            [h, config.n_routed_experts],
            default_initializer=XavierUniform())
        with dtype_guard(config.dtype):  # expert weights in the config dtype
            # SwiGLU experts (reference parity: DeepSeekMoE/Qwen2-MoE/ERNIE
            # experts are gate/up/down; the fused gate‖up keeps it one
            # grouped GEMM) — r5: was a plain 2-matmul silu FFN
            self.experts = GroupedMLP(config.n_routed_experts, h,
                                      config.moe_intermediate_size,
                                      activation="swiglu")
        # expert parallelism: when constructed under a hybrid topology, the
        # expert dim shards over the data axes (the reference's moe group
        # defaults to the dp communicator) and the dispatch einsums become
        # all_to_alls at the EP boundary
        self._ep_axes = shard_grouped_experts(
            self.experts, default_ep_axes(config.n_routed_experts))
        if config.n_shared_experts > 0:
            shared_cfg = dataclasses.replace(
                config,
                intermediate_size=config.moe_intermediate_size
                * config.n_shared_experts)
            self.shared_expert = LlamaMLP(shared_cfg)
        else:
            self.shared_expert = None
        if getattr(config, "moe_correction_bias", False):
            self.e_score_correction_bias = self.create_parameter(
                [config.n_routed_experts],
                default_initializer=Constant(0.0))
        else:
            self.e_score_correction_bias = None
        if getattr(config, "shared_expert_gate", False):
            # Qwen2-MoE: the shared expert's output is scaled by a learned
            # per-token sigmoid gate (modeling_qwen2_moe shared_expert_gate)
            self.shared_gate_weight = self.create_parameter(
                [h, 1], default_initializer=XavierUniform())
        else:
            self.shared_gate_weight = None
        self._aux_loss = None

    def _ep_constrain(self, arr):
        """Expert-dim sharding constraint on the [E, C, M] dispatched block
        so GSPMD forms the all_to_all at the dispatch/combine boundary."""
        from ..distributed.moe import ep_constrain

        return ep_constrain(arr, self._ep_axes)

    def forward(self, x):
        from ..distributed.moe import compute_capacity, one_hot_dispatch

        cfg = self.config
        b, s, h = x.shape[0], x.shape[1], x.shape[2]
        k = cfg.num_experts_per_tok
        E = cfg.n_routed_experts

        def route_and_run(xf, gate_w, w1, b1, w2, b2, *sel_bias):
            tokens = xf.reshape(-1, h)
            S = tokens.shape[0]
            logits = (tokens.astype(jnp.float32)
                      @ gate_w.astype(jnp.float32))
            if cfg.moe_scoring_func == "sigmoid":
                # DeepSeek-V3: per-expert sigmoid affinities (top-k over
                # bias-corrected scores; combine weights renormalize below)
                probs = jax.nn.sigmoid(logits)
            elif cfg.moe_scoring_func == "softmax":
                probs = jax.nn.softmax(logits, axis=-1)
            else:
                raise ValueError(
                    f"moe_scoring_func must be 'softmax' or 'sigmoid', got "
                    f"{cfg.moe_scoring_func!r}")
            # aux-free balancing (HF Ernie4_5 moe_statics / DeepSeek-V3):
            # the bias picks the experts, the raw probs weight the combine
            sel = (probs + sel_bias[0].astype(jnp.float32) if sel_bias
                   else probs)
            if cfg.n_group > 1:
                # group-limited selection (DeepSeek device-limited
                # routing): keep only the topk_group best expert groups
                # per token before the expert top-k. Group score: sum of
                # the group's top-2 affinities under the aux-free bias
                # (V3 noaux_tc), else the group max (V2
                # group_limited_greedy).
                G = cfg.n_group
                if E % G != 0:
                    raise ValueError(
                        f"n_routed_experts {E} not divisible by n_group {G}")
                if k > cfg.topk_group * (E // G):
                    # top_k past the surviving experts would hand real
                    # combine weight to -inf-masked (out-of-group) experts
                    raise ValueError(
                        f"num_experts_per_tok {k} exceeds the "
                        f"{cfg.topk_group} allowed group(s) x {E // G} "
                        f"experts/group")
                sel_g = sel.reshape(S, G, E // G)
                if sel_bias:
                    top2, _ = jax.lax.top_k(sel_g, min(2, E // G))
                    gscore = top2.sum(-1)
                else:
                    gscore = sel_g.max(-1)
                _, gidx = jax.lax.top_k(gscore, cfg.topk_group)
                gmask = jnp.zeros((S, G), bool).at[
                    jnp.arange(S)[:, None], gidx].set(True)
                sel = jnp.where(jnp.repeat(gmask, E // G, axis=1),
                                sel, -jnp.inf)
            _, topk_idx = jax.lax.top_k(sel, k)
            topk_p = jnp.take_along_axis(probs, topk_idx, axis=-1)
            if cfg.norm_topk_prob:
                topk_p = topk_p / jnp.maximum(
                    topk_p.sum(-1, keepdims=True), 1e-20)
            # re-scatter the (possibly renormalized) top-k weights to [S, E]
            weights = jnp.zeros((S, E), probs.dtype).at[
                jnp.arange(S)[:, None], topk_idx].set(topk_p)
            cap = compute_capacity(S, E, k, cfg.moe_capacity_factor)
            combine, dispatch = one_hot_dispatch(weights, topk_idx, cap)
            # dispatch tokens: [S,E,C] x [S,M] -> [E,C,M]
            xe = jnp.einsum("sec,sm->ecm", dispatch.astype(tokens.dtype),
                            tokens)
            xe = self._ep_constrain(xe)  # all_to_all boundary (EP)
            from ..distributed.moe import _grouped_ffn

            ye = _grouped_ffn(xe, w1, b1, w2, b2, "swiglu")
            ye = self._ep_constrain(ye)
            out = jnp.einsum("sec,ecm->sm", combine.astype(ye.dtype), ye)
            if cfg.routed_scaling_factor != 1.0:
                out = out * jnp.asarray(cfg.routed_scaling_factor, ye.dtype)
            # Switch-style aux loss on the router DISTRIBUTION — sigmoid
            # affinities don't sum to 1, so the load measure always uses
            # the softmax of the logits
            dist = (probs if cfg.moe_scoring_func == "softmax"
                    else jax.nn.softmax(logits, axis=-1))
            me = dist.mean(0)
            ce = jax.nn.one_hot(topk_idx[:, 0], E,
                                dtype=dist.dtype).mean(0)
            aux = E * jnp.sum(me * ce)
            return out.reshape(b, s, h).astype(xf.dtype), aux

        extra = ([self.e_score_correction_bias]
                 if self.e_score_correction_bias is not None else [])
        out, aux = apply("moe_mlp", route_and_run, x, self.gate_weight,
                         self.experts.w1, self.experts.b1,
                         self.experts.w2, self.experts.b2, *extra)
        self._aux_loss = aux
        if self.shared_expert is not None:
            shared = self.shared_expert(x)
            if self.shared_gate_weight is not None:
                # through apply(): the eager tape must record the gate so
                # shared_gate_weight trains outside jit too
                shared = apply(
                    "moe_shared_gate",
                    lambda xx, gw, sh: jax.nn.sigmoid(
                        xx.astype(jnp.float32) @ gw.astype(jnp.float32)
                    ).astype(sh.dtype) * sh,
                    x, self.shared_gate_weight, shared)
            out = out + shared
        return out


class LlamaMoEDecoderLayer(Layer):
    """Llama attention block + (dense | MoE) FFN."""

    attn_cls = LlamaAttention  # subclasses (DeepSeek MLA) swap the block

    def __init__(self, config: LlamaMoEConfig, layer_idx: int):
        from .llama import layer_window

        super().__init__(dtype=config.dtype)
        self.self_attn = type(self).attn_cls(config)
        # per-layer window schedule (layer_types) applies to MoE trunks too;
        # attention classes without window support (MLA) must refuse rather
        # than silently attend fully
        if hasattr(self.self_attn, "window"):
            self.self_attn.window = layer_window(config, layer_idx)
        elif getattr(config, "layer_types", None):
            raise NotImplementedError(
                f"{type(self.self_attn).__name__} does not support the "
                "per-layer window schedule (layer_types)")
        self.is_moe = layer_idx >= config.first_k_dense_replace
        self.mlp = MoEMLP(config) if self.is_moe else LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden_states, cos, sin, attention_mask=None,
                kv_cache=None):
        from ..ops.pallas import fused_norm

        residual = hidden_states
        hidden_states = self.input_layernorm(hidden_states)
        if kv_cache is not None:
            hidden_states, kv_cache = self.self_attn(
                hidden_states, cos, sin, attention_mask, kv_cache)
        else:
            hidden_states = self.self_attn(hidden_states, cos, sin,
                                           attention_mask)
        eps = self.post_attention_layernorm.variance_epsilon
        hidden_states, residual = apply(
            "add_rms_norm",
            lambda a, r, w: fused_norm.add_rms_norm(a, r, w, eps),
            hidden_states, residual,
            self.post_attention_layernorm.effective_weight())
        hidden_states = residual + self.mlp(hidden_states)
        if kv_cache is not None:
            return hidden_states, kv_cache
        return hidden_states


class LlamaMoEModel(LlamaModel):
    """LlamaModel with MoE decoder layers (embed/rope/norm reused)."""

    def __init__(self, config: LlamaMoEConfig):
        # build the base with 0 layers, then install MoE layers (the
        # per-layer schedule validates against num_hidden_layers, so it is
        # cleared for the 0-layer shell and read from the REAL config)
        base_cfg = dataclasses.replace(config, num_hidden_layers=0,
                                       layer_types=None)
        super().__init__(base_cfg)
        self.config = config
        self.layers = nn.LayerList(
            [LlamaMoEDecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])


class LlamaMoEForCausalLM(LlamaForCausalLM):
    """DeepSeekMoE/Qwen2-MoE-style causal LM.

    ``forward(..., labels=...)`` adds ``router_aux_loss_coef`` × the mean
    Switch aux loss over the MoE layers to the LM loss (load balancing)."""

    model_cls = LlamaMoEModel  # subclasses (DeepSeek MLA) swap the trunk

    def __init__(self, config: LlamaMoEConfig):
        Layer.__init__(self, dtype=config.dtype)
        self.config = config
        self.llama = type(self).model_cls(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            from .llama import _make_linear

            self.lm_head = _make_linear(config.hidden_size, config.vocab_size,
                                        column=True, config=config,
                                        gather_output=True)
            self.lm_head.weight._array = (
                Normal(0.0, config.initializer_range)(
                    (config.hidden_size, config.vocab_size), jnp.float32)
                .astype(self.lm_head.weight.dtype))

    def aux_loss(self, extra_layers=()):
        """Mean router aux over every MoE layer that ran — the trunk's,
        plus any ``extra_layers`` (the DeepSeek MTP depth blocks)."""
        losses = [l.mlp._aux_loss
                  for l in list(self.llama.layers) + list(extra_layers)
                  if getattr(l, "is_moe", False)
                  and l.mlp._aux_loss is not None]
        if not losses:
            return None
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        return total / len(losses)

    def forward(self, input_ids, labels=None, attention_mask=None):
        out = super().forward(input_ids, labels=labels,
                              attention_mask=attention_mask)
        if labels is None:
            return out
        loss, logits = out
        aux = self.aux_loss()
        if aux is not None:
            loss = loss + self.config.router_aux_loss_coef * aux
        return loss, logits
