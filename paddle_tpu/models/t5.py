"""T5 encoder-decoder family (relative position bias, cross-attention).

Role parity: the encoder-decoder class of the reference ecosystem's model
zoo (PaddleNLP t5/bart modeling). Architecture per the T5 paper / HF
implementation: shared token embedding, T5LayerNorm (= RMSNorm), bucketed
relative position bias computed by the FIRST self-attention layer of each
stack and shared down the stack, cross-attention without position bias,
relu (v1.0) or gated-gelu (v1.1) FFN, tied lm head scaled by
d_model**-0.5 when tied.

TPU-native design: the encoder runs ONCE; decode carries (a) per-layer
self-attention KV buffers written in place at a scalar position — the
same static-shape cache discipline as the decoder-only families — and
(b) per-layer cross-attention K/V projected ONCE from the encoder output.
The whole decode step (embed → all blocks → logits) is one jitted
dispatch.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn.layer import Layer
from ..nn.initializer import Normal
from ..ops.registry import apply
from ..ops.pallas import fused_norm
from ..tensor_class import Tensor, unwrap, wrap

# sentinel: "caller did not pass eos_token_id" — maps to the config
# default; an explicit None DISABLES eos (matching the decoder-only
# families' semantics)
_UNSET = object()

@dataclasses.dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6                  # encoder layers
    num_decoder_layers: Optional[int] = None
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"      # or "gated-gelu" (v1.1)
    tie_word_embeddings: bool = True
    initializer_factor: float = 1.0
    decoder_start_token_id: int = 0
    eos_token_id: int = 1
    pad_token_id: int = 0
    dtype: str = "float32"

    def __post_init__(self):
        if self.num_decoder_layers is None:
            self.num_decoder_layers = self.num_layers
        if self.feed_forward_proj not in ("relu", "gated-gelu"):
            raise ValueError(
                f"feed_forward_proj must be 'relu' or 'gated-gelu', got "
                f"{self.feed_forward_proj!r}")

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, d_model=64, d_kv=16, d_ff=128,
                    num_layers=2, num_heads=4, dtype="float32")
        base.update(kw)
        return T5Config(**base)


def _rel_position_bucket(rel, bidirectional, num_buckets, max_distance):
    """HF T5 bucketing: exact small distances, log-spaced large ones."""
    ret = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        ret = ret + (rel > 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(rel)
    else:
        n = jnp.maximum(-rel, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


class T5LayerNorm(Layer):
    """RMS norm, no bias, no mean subtraction (the T5 norm)."""

    def __init__(self, config: T5Config):
        super().__init__(dtype=config.dtype)
        from ..nn.initializer import Constant

        self.weight = self.create_parameter(
            [config.d_model], default_initializer=Constant(1.0),
            dtype=config.dtype)
        self._eps = config.layer_norm_epsilon

    def forward(self, x):
        eps = self._eps
        return apply("rms_norm", lambda a, w: fused_norm.rms_norm(a, w, eps),
                     x, self.weight)


class T5Attention(Layer):
    """Multi-head attention, no projection biases, NO 1/sqrt(d) scaling
    (T5 folds the scale into the init). Self- or cross-; the first
    self-attention of a stack owns the relative position bias table."""

    def __init__(self, config: T5Config, has_relative_bias=False,
                 bidirectional=True):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.config = config
        self.n_heads = config.num_heads
        self.d_kv = config.d_kv
        inner = config.num_heads * config.d_kv
        with dtype_guard(config.dtype):
            self.q = nn.Linear(config.d_model, inner, bias_attr=False)
            self.k = nn.Linear(config.d_model, inner, bias_attr=False)
            self.v = nn.Linear(config.d_model, inner, bias_attr=False)
            self.o = nn.Linear(inner, config.d_model, bias_attr=False)
        self.has_relative_bias = has_relative_bias
        self.bidirectional = bidirectional
        if has_relative_bias:
            with dtype_guard(config.dtype):
                self.relative_attention_bias = nn.Embedding(
                    config.relative_attention_num_buckets, config.num_heads)

    def compute_bias(self, q_len, kv_len, q_offset=0):
        """[1, heads, q_len, kv_len] additive bias."""
        ctx = jnp.arange(q_len)[:, None] + q_offset
        mem = jnp.arange(kv_len)[None, :]
        buckets = _rel_position_bucket(
            mem - ctx, self.bidirectional,
            self.config.relative_attention_num_buckets,
            self.config.relative_attention_max_distance)
        table = unwrap(self.relative_attention_bias.weight)
        bias = jnp.take(table, buckets, axis=0)       # [q, kv, heads]
        return jnp.moveaxis(bias, 2, 0)[None]         # [1, h, q, kv]

    def compute_bias_rows(self, lengths, kv_len):
        """PER-ROW bias for ragged single-token decode (the seq2seq
        serving engine): [B, heads, 1, kv_len] with row r's query at
        position lengths[r] — the same bucketing/table as compute_bias,
        kept on the layer that owns the table."""
        mem = jnp.arange(kv_len)[None, :]
        buckets = _rel_position_bucket(
            mem - lengths[:, None], self.bidirectional,
            self.config.relative_attention_num_buckets,
            self.config.relative_attention_max_distance)   # [B, kv]
        table = unwrap(self.relative_attention_bias.weight)
        bias = jnp.take(table, buckets, axis=0)            # [B, kv, h]
        return jnp.moveaxis(bias, 2, 1)[:, :, None, :]     # [B, h, 1, kv]

    def _split(self, t, b):
        return t.reshape([b, -1, self.n_heads, self.d_kv])

    def forward(self, hidden, kv_hidden=None, bias=None, mask=None,
                kv_cache=None):
        """bias: [1, h, q, kv] additive (position bias [+ causal/pad]);
        kv_hidden: encoder output for cross-attention; kv_cache: dict with
        'k'/'v' [B, max_len, h, d] + scalar 'pos' for cached self-attn, or
        precomputed {'k': K, 'v': V} (no 'pos': static) for cross-attn."""
        b = hidden.shape[0]
        q = self._split(self.q(hidden), b)

        def attend(qh, kh, vh, add_bias):
            scores = jnp.einsum("bqhd,bkhd->bhqk",
                                unwrap(qh).astype(jnp.float32),
                                unwrap(kh).astype(jnp.float32))
            if add_bias is not None:
                scores = scores + add_bias.astype(jnp.float32)
            probs = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                             unwrap(vh).astype(jnp.float32))
            return out.astype(unwrap(qh).dtype)

        if (isinstance(kv_cache, dict) and "pos" not in kv_cache
                and "lengths" not in kv_cache):
            # cached cross-attention: K/V projected once from the encoder;
            # the encoder pad mask rides the cache (pad columns must stay
            # invisible at every decode step, not just inside the encoder)
            add = bias
            cmask = kv_cache.get("mask")
            if cmask is not None:
                m = jnp.where(cmask[:, None, None, :], 0.0, -jnp.inf)
                add = m if add is None else add + m
            out = attend(q, kv_cache["k"], kv_cache["v"], add)
            return self.o(wrap(out.reshape(b, -1, self.n_heads * self.d_kv))), kv_cache
        if isinstance(kv_cache, dict) and "lengths" in kv_cache:
            # RAGGED single-token decode (the seq2seq serving engine):
            # row r writes at ITS length and attends columns 0..lengths[r];
            # the caller supplies the PER-ROW relative bias [B, h, 1, T]
            s = hidden.shape[1]
            if s != 1:
                raise ValueError("ragged T5 decode is single-token")
            lengths = kv_cache["lengths"]
            k_new = self._split(self.k(hidden), b)
            v_new = self._split(self.v(hidden), b)
            rows = jnp.arange(b)
            k_buf = kv_cache["k"].at[rows, lengths].set(
                unwrap(k_new)[:, 0].astype(kv_cache["k"].dtype))
            v_buf = kv_cache["v"].at[rows, lengths].set(
                unwrap(v_new)[:, 0].astype(kv_cache["v"].dtype))
            t_idx = jnp.arange(k_buf.shape[1])
            valid = t_idx[None, :] <= lengths[:, None]
            add = jnp.where(valid[:, None, None, :], 0.0, -jnp.inf)
            if bias is not None:
                add = add + bias.astype(jnp.float32)
            out = attend(q, k_buf, v_buf, add)
            new = {"k": k_buf, "v": v_buf, "lengths": lengths + 1}
            return self.o(wrap(out.reshape(b, s, self.n_heads * self.d_kv))), new
        if isinstance(kv_cache, dict):
            # cached causal self-attention at scalar position pos
            s = hidden.shape[1]
            k_new = self._split(self.k(hidden), b)
            v_new = self._split(self.v(hidden), b)
            pos = kv_cache["pos"]
            k_buf = jax.lax.dynamic_update_slice(
                kv_cache["k"], unwrap(k_new).astype(kv_cache["k"].dtype),
                (0, pos, 0, 0))
            v_buf = jax.lax.dynamic_update_slice(
                kv_cache["v"], unwrap(v_new).astype(kv_cache["v"].dtype),
                (0, pos, 0, 0))
            t_idx = jnp.arange(k_buf.shape[1])
            s_idx = jnp.arange(s)
            valid = t_idx[None, :] <= (pos + s_idx)[:, None]
            add = jnp.where(valid[None, None], 0.0, -jnp.inf)
            if bias is not None:
                add = add + bias
            out = attend(q, k_buf, v_buf, add)
            new = {"k": k_buf, "v": v_buf, "pos": pos + s}
            return self.o(wrap(out.reshape(b, s, self.n_heads * self.d_kv))), new
        src = hidden if kv_hidden is None else kv_hidden
        k = self._split(self.k(src), b)
        v = self._split(self.v(src), b)
        add = bias
        if mask is not None:  # [B, kv] validity
            m = jnp.where(mask[:, None, None, :], 0.0, -jnp.inf)
            add = m if add is None else add + m
        out = attend(q, k, v, add)
        return self.o(wrap(out.reshape(b, -1, self.n_heads * self.d_kv)))


class T5FF(Layer):
    def __init__(self, config: T5Config):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.gated = config.feed_forward_proj == "gated-gelu"
        with dtype_guard(config.dtype):
            if self.gated:
                self.wi_0 = nn.Linear(config.d_model, config.d_ff, bias_attr=False)
                self.wi_1 = nn.Linear(config.d_model, config.d_ff, bias_attr=False)
            else:
                self.wi = nn.Linear(config.d_model, config.d_ff, bias_attr=False)
            self.wo = nn.Linear(config.d_ff, config.d_model, bias_attr=False)

    def forward(self, x):
        if self.gated:
            act = apply("gelu_tanh",
                        lambda a: jax.nn.gelu(a, approximate=True),
                        self.wi_0(x))
            return self.wo(act * self.wi_1(x))
        return self.wo(apply("relu", jax.nn.relu, self.wi(x)))


class T5Block(Layer):
    """Pre-norm residual block: self-attn [,cross-attn], FFN."""

    def __init__(self, config: T5Config, is_decoder, has_relative_bias):
        super().__init__(dtype=config.dtype)
        self.is_decoder = is_decoder
        self.ln_self = T5LayerNorm(config)
        self.self_attn = T5Attention(config, has_relative_bias,
                                     bidirectional=not is_decoder)
        if is_decoder:
            self.ln_cross = T5LayerNorm(config)
            self.cross_attn = T5Attention(config, False)
        self.ln_ff = T5LayerNorm(config)
        self.ff = T5FF(config)

    def forward(self, hidden, bias=None, enc_hidden=None, enc_mask=None,
                self_cache=None, cross_cache=None, mask=None):
        if self_cache is not None:
            a, self_cache = self.self_attn(self.ln_self(hidden), bias=bias,
                                           kv_cache=self_cache)
        else:
            a = self.self_attn(self.ln_self(hidden), bias=bias, mask=mask)
        hidden = hidden + a
        if self.is_decoder and (enc_hidden is not None
                                or cross_cache is not None):
            if cross_cache is not None:
                c, cross_cache = self.cross_attn(self.ln_cross(hidden),
                                                 bias=None,
                                                 kv_cache=cross_cache)
            else:
                c = self.cross_attn(self.ln_cross(hidden),
                                    kv_hidden=enc_hidden, mask=enc_mask)
            hidden = hidden + c
        hidden = hidden + self.ff(self.ln_ff(hidden))
        if self_cache is not None:
            return hidden, self_cache, cross_cache
        return hidden


class T5Stack(Layer):
    def __init__(self, config: T5Config, is_decoder, shared_embed):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.is_decoder = is_decoder
        self.embed = shared_embed
        n = config.num_decoder_layers if is_decoder else config.num_layers
        self.blocks = nn.LayerList(
            [T5Block(config, is_decoder, has_relative_bias=(i == 0))
             for i in range(n)])
        self.final_norm = T5LayerNorm(config)

    def _bias(self, q_len, kv_len, q_offset=0, causal=False):
        bias = self.blocks[0].self_attn.compute_bias(q_len, kv_len, q_offset)
        if causal:
            rows = jnp.arange(q_len)[:, None] + q_offset
            cols = jnp.arange(kv_len)[None, :]
            bias = bias + jnp.where(cols <= rows, 0.0, -jnp.inf)[None, None]
        return bias

    def forward(self, ids, enc_hidden=None, enc_mask=None, mask=None):
        s = ids.shape[1]
        hidden = self.embed(ids)
        bias = self._bias(s, s, causal=self.is_decoder)
        for block in self.blocks:
            hidden = block(hidden, bias=bias, enc_hidden=enc_hidden,
                           enc_mask=enc_mask, mask=mask)
        return self.final_norm(hidden)

    def forward_cached(self, ids, self_caches, cross_caches):
        """Decoder step(s) at the caches' scalar position — or at
        per-row positions when the caches carry "lengths" (the seq2seq
        serving engine's ragged rows)."""
        s = ids.shape[1]
        hidden = self.embed(ids)
        max_len = self_caches[0]["k"].shape[1]
        if "lengths" in self_caches[0]:
            bias = self.blocks[0].self_attn.compute_bias_rows(
                self_caches[0]["lengths"], max_len)
        else:
            pos = self_caches[0]["pos"]
            bias = self._bias(s, max_len, q_offset=pos)
        new_self, new_cross = [], []
        for block, sc, cc in zip(self.blocks, self_caches, cross_caches):
            hidden, sc, cc = block(hidden, bias=bias, self_cache=sc,
                                   cross_cache=cc)
            new_self.append(sc)
            new_cross.append(cc)
        return self.final_norm(hidden), new_self, new_cross


class T5ForConditionalGeneration(Layer):
    """T5 encoder-decoder LM (HF-compatible semantics incl. the
    d_model**-0.5 logit scaling under tied embeddings)."""

    def __init__(self, config: T5Config):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.config = config
        with dtype_guard(config.dtype):
            self.shared = nn.Embedding(config.vocab_size, config.d_model)
        self.shared.weight._array = (
            Normal(0.0, config.initializer_factor)(
                (config.vocab_size, config.d_model), jnp.float32)
            .astype(self.shared.weight.dtype))
        self.encoder = T5Stack(config, is_decoder=False,
                               shared_embed=self.shared)
        self.decoder = T5Stack(config, is_decoder=True,
                               shared_embed=self.shared)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            with dtype_guard(config.dtype):
                self.lm_head = nn.Linear(config.d_model, config.vocab_size,
                                         bias_attr=False)

    def lm_head_logits(self, hidden):
        if self.lm_head is None:
            from .llama import tied_lm_head_logits

            scaled = hidden * (self.config.d_model ** -0.5)
            return tied_lm_head_logits(scaled, self.shared.weight)
        return self.lm_head(hidden)

    def forward(self, input_ids, decoder_input_ids, attention_mask=None,
                labels=None):
        enc = self.encoder(input_ids, mask=attention_mask)
        dec = self.decoder(decoder_input_ids, enc_hidden=enc,
                           enc_mask=attention_mask)
        logits = self.lm_head_logits(dec)
        if labels is None:
            return logits
        from .llama import causal_lm_loss

        return causal_lm_loss(logits, labels), logits

    # ---- cached generation ---------------------------------------------------
    def _init_caches(self, enc, batch, max_len, enc_mask=None):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        self_caches, cross_caches = [], []
        for block in self.decoder.blocks:
            self_caches.append({
                "k": jnp.zeros((batch, max_len, cfg.num_heads, cfg.d_kv), dt),
                "v": jnp.zeros((batch, max_len, cfg.num_heads, cfg.d_kv), dt),
                "pos": jnp.asarray(0, jnp.int32)})
            ca = block.cross_attn
            k = ca._split(ca.k(enc), enc.shape[0])
            v = ca._split(ca.v(enc), enc.shape[0])
            # no "pos" key marks a STATIC (cross-attention) cache
            cc = {"k": unwrap(k), "v": unwrap(v)}
            if enc_mask is not None:
                cc["mask"] = enc_mask
            cross_caches.append(cc)
        return self_caches, cross_caches

    def generate(self, input_ids, max_new_tokens=20, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=_UNSET,
                 attention_mask=None, num_beams=1, length_penalty=1.0,
                 early_stopping=False, **unsupported):
        """Encoder once, then jitted cached decoder steps from
        decoder_start_token_id; stops when every row emits eos.
        ``num_beams > 1`` runs the shared host-scored beam search over the
        cached decoder (HF num_beams semantics)."""
        from ..generation import reject_non_default_kwargs

        reject_non_default_kwargs("T5", unsupported)
        from ..generation import reject_sampled_beams

        reject_sampled_beams("T5", num_beams, do_sample)
        from ..autograd import tape as _tape
        from ..framework import random as _random
        from ..generation import _select, encdec_beam_generate

        cfg = self.config
        eos = cfg.eos_token_id if eos_token_id is _UNSET else eos_token_id
        ids = unwrap(input_ids) if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        B = ids.shape[0]
        am = attention_mask
        if am is not None:
            am = (unwrap(am) if isinstance(am, Tensor)
                  else jnp.asarray(am)).astype(bool)
        with _tape.no_grad():
            enc = self.encoder(wrap(ids), mask=am)
            self_c, cross_c = self._init_caches(enc, B, max_new_tokens,
                                                enc_mask=am)
            step = _get_t5_decode_step(self, max_new_tokens)
            token = jnp.full((B, 1), cfg.decoder_start_token_id, jnp.int32)
            if num_beams > 1:
                return encdec_beam_generate(
                    self,
                    lambda m, t, s, c: m.decoder.forward_cached(t, s, c),
                    step, token, self_c, cross_c, max_new_tokens,
                    num_beams, eos, length_penalty, early_stopping,
                    "_t5_beam_steps")
            finished = jnp.zeros((B,), bool)
            out = []
            for i in range(max_new_tokens):
                logits, self_c = step(token, self_c, cross_c)
                nxt = _select(logits[:, -1, :], _random.next_key(),
                              do_sample, float(temperature), int(top_k),
                              float(top_p))
                if eos is not None:
                    nxt = jnp.where(finished, eos, nxt)
                    finished = finished | (nxt == eos)
                token = nxt[:, None].astype(jnp.int32)
                out.append(token)
                if eos is not None and bool(finished.all()):
                    break
            return wrap(jnp.concatenate(out, axis=1))

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


class _T5DecodeStep:
    """One jitted decoder step: embed → all blocks (cached self-attn +
    static cross-attn) → logits."""

    def __init__(self, model, max_len):
        from ..autograd import tape as _tape
        from ..nn.layer import functional_weights

        def pure(state, token, self_caches, cross_caches):
            with functional_weights(model, state), _tape.no_grad():
                hidden, new_self, _ = model.decoder.forward_cached(
                    wrap(token), self_caches, cross_caches)
                logits = model.lm_head_logits(hidden)
            return unwrap(logits), [
                {k: (unwrap(v) if isinstance(v, Tensor) else v)
                 for k, v in c.items()} for c in new_self]

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, token, self_caches, cross_caches):
        return self._jitted(self._state, token, self_caches, cross_caches)


def _get_t5_decode_step(model, max_len):
    from ..generation import _memoized_step

    return _memoized_step(model, "_t5_decode_steps", (max_len,),
                          lambda: _T5DecodeStep(model, max_len))


# ---------------------------------------------------------------------------
# HuggingFace checkpoint interop
# ---------------------------------------------------------------------------

def t5_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a T5ForConditionalGeneration from a transformers T5 model."""
    from .llama import _hf_to_np

    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    from .llama import _hf_get

    get = _hf_get(hf_config)
    ff = get("feed_forward_proj", "relu")
    kw = dict(vocab_size=get("vocab_size"), d_model=get("d_model"),
              d_kv=get("d_kv"), d_ff=get("d_ff"),
              num_layers=get("num_layers"),
              num_decoder_layers=get("num_decoder_layers"),
              num_heads=get("num_heads"),
              relative_attention_num_buckets=get(
                  "relative_attention_num_buckets", 32),
              relative_attention_max_distance=get(
                  "relative_attention_max_distance", 128),
              layer_norm_epsilon=get("layer_norm_epsilon", 1e-6),
              feed_forward_proj=("gated-gelu" if "gated" in ff else "relu"),
              tie_word_embeddings=bool(get("tie_word_embeddings", True)),
              decoder_start_token_id=get("decoder_start_token_id", 0),
              eos_token_id=get("eos_token_id", 1),
              pad_token_id=get("pad_token_id", 0))
    kw.update(config_overrides)
    cfg = T5Config(**kw)
    model = T5ForConditionalGeneration(cfg)

    plan = {"shared.weight": ("shared.weight", False)}
    for side, stack, n in (("encoder", model.encoder, cfg.num_layers),
                           ("decoder", model.decoder,
                            cfg.num_decoder_layers)):
        plan[f"{side}.final_norm.weight"] = (
            f"{side}.final_layer_norm.weight", False)
        is_dec = side == "decoder"
        for i in range(n):
            hf = f"{side}.block.{i}.layer"
            ours = f"{side}.blocks.{i}"
            for proj in "qkvo":
                plan[f"{ours}.self_attn.{proj}.weight"] = (
                    f"{hf}.0.SelfAttention.{proj}.weight", True)
            plan[f"{ours}.ln_self.weight"] = (f"{hf}.0.layer_norm.weight",
                                              False)
            if i == 0:
                plan[f"{ours}.self_attn.relative_attention_bias.weight"] = (
                    f"{hf}.0.SelfAttention.relative_attention_bias.weight",
                    False)
            ff_idx = 1
            if is_dec:
                for proj in "qkvo":
                    plan[f"{ours}.cross_attn.{proj}.weight"] = (
                        f"{hf}.1.EncDecAttention.{proj}.weight", True)
                plan[f"{ours}.ln_cross.weight"] = (
                    f"{hf}.1.layer_norm.weight", False)
                ff_idx = 2
            if cfg.feed_forward_proj == "gated-gelu":
                plan[f"{ours}.ff.wi_0.weight"] = (
                    f"{hf}.{ff_idx}.DenseReluDense.wi_0.weight", True)
                plan[f"{ours}.ff.wi_1.weight"] = (
                    f"{hf}.{ff_idx}.DenseReluDense.wi_1.weight", True)
            else:
                plan[f"{ours}.ff.wi.weight"] = (
                    f"{hf}.{ff_idx}.DenseReluDense.wi.weight", True)
            plan[f"{ours}.ff.wo.weight"] = (
                f"{hf}.{ff_idx}.DenseReluDense.wo.weight", True)
            plan[f"{ours}.ln_ff.weight"] = (
                f"{hf}.{ff_idx}.layer_norm.weight", False)
    if not cfg.tie_word_embeddings:
        plan["lm_head.weight"] = ("lm_head.weight", True)

    mapped, consumed = {}, set()
    for name, (hf_key, transpose) in plan.items():
        if hf_key not in state:
            raise KeyError(f"t5_from_hf: checkpoint is missing {hf_key!r}")
        v = _hf_to_np(state[hf_key])
        mapped[name] = v.T if transpose else v
        consumed.add(hf_key)
    leftovers = [k for k in state
                 if k not in consumed and k != "lm_head.weight"
                 and "embed_tokens" not in k]   # stack aliases of shared
    if leftovers:
        raise ValueError(
            f"t5_from_hf: checkpoint tensors this model cannot represent: "
            f"{leftovers[:5]}{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected
    if missing:
        raise KeyError(f"t5_from_hf: model keys not covered: {missing[:5]}")
    return model
