"""BART encoder-decoder family (post-LN, learned positions).

Role parity: the second seq2seq flagship of the reference ecosystem's
zoo (PaddleNLP bart/mbart modeling). Architecture per HF: learned
position embeddings with the +2 offset quirk, POST-layer-norm residual
blocks (LayerNorm after the residual add), scaled dot-product attention
with biases on every projection, gelu FFN with biases, tied lm head plus
a final_logits_bias row.

TPU-native design mirrors models/t5.py: the encoder runs once, cross
K/V are projected once, and each decoder step is one jitted dispatch
over in-place self-attention KV buffers; positions ride the caches'
scalar offset.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn.layer import Layer
from ..ops.registry import apply
from ..tensor_class import Tensor, Parameter, unwrap, wrap

# sentinel: "caller did not pass eos_token_id" — maps to the config
# default; an explicit None DISABLES eos (matching the decoder-only
# families' semantics)
_UNSET = object()

@dataclasses.dataclass
class BartConfig:
    vocab_size: int = 50265
    d_model: int = 768
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 12
    decoder_attention_heads: int = 12
    encoder_ffn_dim: int = 3072
    decoder_ffn_dim: int = 3072
    max_position_embeddings: int = 1024
    activation_function: str = "gelu"     # "gelu" | "gelu_new" | "relu"
    scale_embedding: bool = False
    decoder_start_token_id: int = 2
    eos_token_id: int = 2
    pad_token_id: int = 1
    dtype: str = "float32"

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, d_model=64, encoder_layers=2,
                    decoder_layers=2, encoder_attention_heads=4,
                    decoder_attention_heads=4, encoder_ffn_dim=128,
                    decoder_ffn_dim=128, max_position_embeddings=128,
                    dtype="float32")
        base.update(kw)
        return BartConfig(**base)

    def __post_init__(self):
        if self.activation_function not in ("gelu", "gelu_new", "relu"):
            raise NotImplementedError(
                f"BART activation_function {self.activation_function!r} "
                "(supported: gelu, gelu_new, relu)")


_POS_OFFSET = 2  # HF BartLearnedPositionalEmbedding reserves 2 rows


def _activation(config):
    if config.activation_function == "relu":
        return "relu", jax.nn.relu
    approx = config.activation_function == "gelu_new"
    return ("gelu_tanh" if approx else "gelu",
            lambda a: jax.nn.gelu(a, approximate=approx))


class BartAttention(Layer):
    """Scaled MHA with biases; self- (optionally cached) or cross-
    (static cached K/V) attention — the cache discipline of models/t5.py
    with BART's scaling and biases."""

    def __init__(self, config: BartConfig, n_heads: int):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.n_heads = n_heads
        self.head_dim = config.d_model // n_heads
        self.scale = 1.0 / math.sqrt(self.head_dim)
        with dtype_guard(config.dtype):
            self.q_proj = nn.Linear(config.d_model, config.d_model)
            self.k_proj = nn.Linear(config.d_model, config.d_model)
            self.v_proj = nn.Linear(config.d_model, config.d_model)
            self.out_proj = nn.Linear(config.d_model, config.d_model)

    def _split(self, t, b):
        return t.reshape([b, -1, self.n_heads, self.head_dim])

    def forward(self, hidden, kv_hidden=None, mask=None, causal=False,
                kv_cache=None):
        b = hidden.shape[0]
        q = self._split(self.q_proj(hidden), b)
        scale = self.scale

        def attend(qh, kh, vh, add):
            scores = jnp.einsum("bqhd,bkhd->bhqk",
                                unwrap(qh).astype(jnp.float32),
                                unwrap(kh).astype(jnp.float32)) * scale
            if add is not None:
                scores = scores + add
            probs = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                             unwrap(vh).astype(jnp.float32))
            return out.astype(unwrap(qh).dtype)

        if (isinstance(kv_cache, dict) and "pos" not in kv_cache
                and "lengths" not in kv_cache):
            add = None
            cmask = kv_cache.get("mask")
            if cmask is not None:
                add = jnp.where(cmask[:, None, None, :], 0.0, -jnp.inf)
            out = attend(q, kv_cache["k"], kv_cache["v"], add)
            return self.out_proj(
                wrap(out.reshape(b, -1, self.n_heads * self.head_dim))), kv_cache
        if isinstance(kv_cache, dict) and "lengths" in kv_cache:
            # RAGGED single-token decode (the seq2seq serving engine):
            # row r's new token writes at ITS length and attends columns
            # 0..lengths[r] — slots of different ages share one step
            s = hidden.shape[1]
            if s != 1:
                raise ValueError("ragged enc-dec decode is single-token")
            lengths = kv_cache["lengths"]
            k_new = self._split(self.k_proj(hidden), b)
            v_new = self._split(self.v_proj(hidden), b)
            rows = jnp.arange(b)
            k_buf = kv_cache["k"].at[rows, lengths].set(
                unwrap(k_new)[:, 0].astype(kv_cache["k"].dtype))
            v_buf = kv_cache["v"].at[rows, lengths].set(
                unwrap(v_new)[:, 0].astype(kv_cache["v"].dtype))
            t_idx = jnp.arange(k_buf.shape[1])
            valid = t_idx[None, :] <= lengths[:, None]          # [B, T]
            add = jnp.where(valid[:, None, None, :], 0.0, -jnp.inf)
            out = attend(q, k_buf, v_buf, add)
            new = {"k": k_buf, "v": v_buf, "lengths": lengths + 1}
            return self.out_proj(
                wrap(out.reshape(b, s, self.n_heads * self.head_dim))), new
        if isinstance(kv_cache, dict):
            s = hidden.shape[1]
            k_new = self._split(self.k_proj(hidden), b)
            v_new = self._split(self.v_proj(hidden), b)
            pos = kv_cache["pos"]
            k_buf = jax.lax.dynamic_update_slice(
                kv_cache["k"], unwrap(k_new).astype(kv_cache["k"].dtype),
                (0, pos, 0, 0))
            v_buf = jax.lax.dynamic_update_slice(
                kv_cache["v"], unwrap(v_new).astype(kv_cache["v"].dtype),
                (0, pos, 0, 0))
            t_idx = jnp.arange(k_buf.shape[1])
            s_idx = jnp.arange(s)
            valid = t_idx[None, :] <= (pos + s_idx)[:, None]
            add = jnp.where(valid[None, None], 0.0, -jnp.inf)
            out = attend(q, k_buf, v_buf, add)
            new = {"k": k_buf, "v": v_buf, "pos": pos + s}
            return self.out_proj(
                wrap(out.reshape(b, s, self.n_heads * self.head_dim))), new
        src = hidden if kv_hidden is None else kv_hidden
        k = self._split(self.k_proj(src), b)
        v = self._split(self.v_proj(src), b)
        add = None
        if causal:
            sq, sk = hidden.shape[1], src.shape[1]
            cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            add = jnp.where(cm, 0.0, -jnp.inf)[None, None]
        if mask is not None:
            m = jnp.where(mask[:, None, None, :], 0.0, -jnp.inf)
            add = m if add is None else add + m
        out = attend(q, k, v, add)
        return self.out_proj(
            wrap(out.reshape(b, -1, self.n_heads * self.head_dim)))


class BartEncoderLayer(Layer):
    """POST-LN: x = LN(x + attn(x)); x = LN(x + ffn(x))."""

    def __init__(self, config: BartConfig):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.self_attn = BartAttention(config, config.encoder_attention_heads)
        with dtype_guard(config.dtype):
            self.self_attn_layer_norm = nn.LayerNorm(config.d_model)
            self.fc1 = nn.Linear(config.d_model, config.encoder_ffn_dim)
            self.fc2 = nn.Linear(config.encoder_ffn_dim, config.d_model)
            self.final_layer_norm = nn.LayerNorm(config.d_model)
        self._act = _activation(config)

    def forward(self, hidden, mask=None):
        hidden = self.self_attn_layer_norm(
            hidden + self.self_attn(hidden, mask=mask))
        act = apply(self._act[0], self._act[1], self.fc1(hidden))
        return self.final_layer_norm(hidden + self.fc2(act))


class BartDecoderLayer(Layer):
    def __init__(self, config: BartConfig):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.self_attn = BartAttention(config, config.decoder_attention_heads)
        self.encoder_attn = BartAttention(config,
                                          config.decoder_attention_heads)
        with dtype_guard(config.dtype):
            self.self_attn_layer_norm = nn.LayerNorm(config.d_model)
            self.encoder_attn_layer_norm = nn.LayerNorm(config.d_model)
            self.fc1 = nn.Linear(config.d_model, config.decoder_ffn_dim)
            self.fc2 = nn.Linear(config.decoder_ffn_dim, config.d_model)
            self.final_layer_norm = nn.LayerNorm(config.d_model)
        self._act = _activation(config)

    def forward(self, hidden, enc_hidden=None, enc_mask=None,
                self_cache=None, cross_cache=None):
        if self_cache is not None:
            a, self_cache = self.self_attn(hidden, kv_cache=self_cache)
        else:
            a = self.self_attn(hidden, causal=True)
        hidden = self.self_attn_layer_norm(hidden + a)
        if cross_cache is not None:
            c, cross_cache = self.encoder_attn(hidden, kv_cache=cross_cache)
        else:
            c = self.encoder_attn(hidden, kv_hidden=enc_hidden,
                                  mask=enc_mask)
        hidden = self.encoder_attn_layer_norm(hidden + c)
        act = apply(self._act[0], self._act[1], self.fc1(hidden))
        hidden = self.final_layer_norm(hidden + self.fc2(act))
        if self_cache is not None:
            return hidden, self_cache, cross_cache
        return hidden


class BartModel(Layer):
    def __init__(self, config: BartConfig):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.config = config
        with dtype_guard(config.dtype):
            self.shared = nn.Embedding(config.vocab_size, config.d_model)
            self.encoder_pos = nn.Embedding(
                config.max_position_embeddings + _POS_OFFSET, config.d_model)
            self.decoder_pos = nn.Embedding(
                config.max_position_embeddings + _POS_OFFSET, config.d_model)
            self.encoder_ln_emb = nn.LayerNorm(config.d_model)
            self.decoder_ln_emb = nn.LayerNorm(config.d_model)
        self.encoder_layers_list = nn.LayerList(
            [BartEncoderLayer(config) for _ in range(config.encoder_layers)])
        self.decoder_layers_list = nn.LayerList(
            [BartDecoderLayer(config) for _ in range(config.decoder_layers)])
        self._scale = (math.sqrt(config.d_model)
                       if config.scale_embedding else 1.0)

    def _embed(self, ids, pos_table, positions):
        tok = unwrap(self.shared(ids)) * self._scale
        pe = jnp.take(unwrap(pos_table.weight),
                      jnp.asarray(positions) + _POS_OFFSET, axis=0)
        if pe.ndim == 2:
            pe = pe[None]
        return wrap((tok + pe).astype(jnp.dtype(self.config.dtype)))

    def _check_len(self, s):
        if s > self.config.max_position_embeddings:
            # learned tables are fixed size; clamped take would silently
            # reuse the last row for every overflow position
            raise ValueError(
                f"BART: sequence length {s} exceeds max_position_embeddings "
                f"{self.config.max_position_embeddings}")

    def encode(self, input_ids, mask=None):
        s = input_ids.shape[1]
        self._check_len(s)
        hidden = self.encoder_ln_emb(
            self._embed(input_ids, self.encoder_pos, jnp.arange(s)))
        for layer in self.encoder_layers_list:
            hidden = layer(hidden, mask=mask)
        return hidden

    def decode(self, ids, enc_hidden, enc_mask=None):
        s = ids.shape[1]
        self._check_len(s)
        hidden = self.decoder_ln_emb(
            self._embed(ids, self.decoder_pos, jnp.arange(s)))
        for layer in self.decoder_layers_list:
            hidden = layer(hidden, enc_hidden=enc_hidden, enc_mask=enc_mask)
        return hidden

    def decode_cached(self, ids, self_caches, cross_caches):
        s = ids.shape[1]
        if "lengths" in self_caches[0]:     # ragged serving rows
            positions = (self_caches[0]["lengths"][:, None]
                         + jnp.arange(s)[None, :])
        else:
            positions = self_caches[0]["pos"] + jnp.arange(s)
        hidden = self.decoder_ln_emb(
            self._embed(ids, self.decoder_pos, positions))
        new_self, new_cross = [], []
        for layer, sc, cc in zip(self.decoder_layers_list, self_caches,
                                 cross_caches):
            hidden, sc, cc = layer(hidden, self_cache=sc, cross_cache=cc)
            new_self.append(sc)
            new_cross.append(cc)
        return hidden, new_self, new_cross


class BartForConditionalGeneration(Layer):
    """BART seq2seq LM: tied lm head + final_logits_bias."""

    def __init__(self, config: BartConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.model = BartModel(config)
        self.final_logits_bias = Parameter(
            jnp.zeros((config.vocab_size,), jnp.float32), trainable=False)

    def lm_head_logits(self, hidden):
        from .llama import tied_lm_head_logits

        logits = tied_lm_head_logits(hidden, self.model.shared.weight)
        return logits + wrap(unwrap(self.final_logits_bias).astype(
            unwrap(logits).dtype))

    def forward(self, input_ids, decoder_input_ids, attention_mask=None,
                labels=None):
        enc = self.model.encode(input_ids, mask=attention_mask)
        dec = self.model.decode(decoder_input_ids, enc,
                                enc_mask=attention_mask)
        logits = self.lm_head_logits(dec)
        if labels is None:
            return logits
        from .llama import causal_lm_loss

        return causal_lm_loss(logits, labels), logits

    def _init_caches(self, enc, batch, max_len, enc_mask=None):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        h = cfg.decoder_attention_heads
        d = cfg.d_model // h
        self_caches, cross_caches = [], []
        for layer in self.model.decoder_layers_list:
            self_caches.append({
                "k": jnp.zeros((batch, max_len, h, d), dt),
                "v": jnp.zeros((batch, max_len, h, d), dt),
                "pos": jnp.asarray(0, jnp.int32)})
            ca = layer.encoder_attn
            cc = {"k": unwrap(ca._split(ca.k_proj(enc), enc.shape[0])),
                  "v": unwrap(ca._split(ca.v_proj(enc), enc.shape[0]))}
            if enc_mask is not None:
                cc["mask"] = enc_mask
            cross_caches.append(cc)
        return self_caches, cross_caches

    def generate(self, input_ids, max_new_tokens=20, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=_UNSET,
                 attention_mask=None, num_beams=1, length_penalty=1.0,
                 early_stopping=False, **unsupported):
        from ..generation import reject_non_default_kwargs

        reject_non_default_kwargs("BART", unsupported)
        from ..generation import reject_sampled_beams

        reject_sampled_beams("BART", num_beams, do_sample)
        from ..autograd import tape as _tape
        from ..framework import random as _random
        from ..generation import _select, encdec_beam_generate

        cfg = self.config
        eos = cfg.eos_token_id if eos_token_id is _UNSET else eos_token_id
        ids = unwrap(input_ids) if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        B = ids.shape[0]
        self.model._check_len(int(max_new_tokens))
        am = attention_mask
        if am is not None:
            am = (unwrap(am) if isinstance(am, Tensor)
                  else jnp.asarray(am)).astype(bool)
        with _tape.no_grad():
            enc = self.model.encode(wrap(ids), mask=am)
            self_c, cross_c = self._init_caches(enc, B, max_new_tokens,
                                                enc_mask=am)
            step = _get_bart_decode_step(self, max_new_tokens)
            token = jnp.full((B, 1), cfg.decoder_start_token_id, jnp.int32)
            if num_beams > 1:
                return encdec_beam_generate(
                    self,
                    lambda m, t, s, c: m.model.decode_cached(t, s, c),
                    step, token, self_c, cross_c, max_new_tokens,
                    num_beams, eos, length_penalty, early_stopping,
                    "_bart_beam_steps")
            finished = jnp.zeros((B,), bool)
            out = []
            for i in range(max_new_tokens):
                logits, self_c = step(token, self_c, cross_c)
                nxt = _select(logits[:, -1, :], _random.next_key(),
                              do_sample, float(temperature), int(top_k),
                              float(top_p))
                if eos is not None:
                    nxt = jnp.where(finished, eos, nxt)
                    finished = finished | (nxt == eos)
                token = nxt[:, None].astype(jnp.int32)
                out.append(token)
                if eos is not None and bool(finished.all()):
                    break
            return wrap(jnp.concatenate(out, axis=1))

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


class _BartDecodeStep:
    def __init__(self, model, max_len):
        from ..autograd import tape as _tape
        from ..nn.layer import functional_weights

        def pure(state, token, self_caches, cross_caches):
            with functional_weights(model, state), _tape.no_grad():
                hidden, new_self, _ = model.model.decode_cached(
                    wrap(token), self_caches, cross_caches)
                logits = model.lm_head_logits(hidden)
            return unwrap(logits), [
                {k: (unwrap(v) if isinstance(v, Tensor) else v)
                 for k, v in c.items()} for c in new_self]

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, token, self_caches, cross_caches):
        return self._jitted(self._state, token, self_caches, cross_caches)


def _get_bart_decode_step(model, max_len):
    from ..generation import _memoized_step

    return _memoized_step(model, "_bart_decode_steps", (max_len,),
                          lambda: _BartDecodeStep(model, max_len))


# ---------------------------------------------------------------------------
# HuggingFace checkpoint interop
# ---------------------------------------------------------------------------

def bart_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a BartForConditionalGeneration from a transformers BART."""
    from .llama import _hf_to_np

    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    from .llama import _hf_get

    get = _hf_get(hf_config)
    kw = dict(vocab_size=get("vocab_size"), d_model=get("d_model"),
              encoder_layers=get("encoder_layers"),
              decoder_layers=get("decoder_layers"),
              encoder_attention_heads=get("encoder_attention_heads"),
              decoder_attention_heads=get("decoder_attention_heads"),
              encoder_ffn_dim=get("encoder_ffn_dim"),
              decoder_ffn_dim=get("decoder_ffn_dim"),
              max_position_embeddings=get("max_position_embeddings"),
              activation_function=get("activation_function", "gelu"),
              scale_embedding=bool(get("scale_embedding", False)),
              decoder_start_token_id=get("decoder_start_token_id", 2),
              eos_token_id=get("eos_token_id", 2),
              pad_token_id=get("pad_token_id", 1))
    kw.update(config_overrides)
    cfg = BartConfig(**kw)
    model = BartForConditionalGeneration(cfg)

    plan = {"model.shared.weight": ("model.shared.weight", False),
            "model.encoder_pos.weight": ("model.encoder.embed_positions.weight", False),
            "model.decoder_pos.weight": ("model.decoder.embed_positions.weight", False),
            "model.encoder_ln_emb.weight": ("model.encoder.layernorm_embedding.weight", False),
            "model.encoder_ln_emb.bias": ("model.encoder.layernorm_embedding.bias", False),
            "model.decoder_ln_emb.weight": ("model.decoder.layernorm_embedding.weight", False),
            "model.decoder_ln_emb.bias": ("model.decoder.layernorm_embedding.bias", False),
            "final_logits_bias": ("final_logits_bias", False)}
    attn_mods = ("q_proj", "k_proj", "v_proj", "out_proj")
    for side, n, ours_list in (("encoder", cfg.encoder_layers,
                                "encoder_layers_list"),
                               ("decoder", cfg.decoder_layers,
                                "decoder_layers_list")):
        for i in range(n):
            hf = f"model.{side}.layers.{i}"
            ours = f"model.{ours_list}.{i}"
            attns = [("self_attn", "self_attn")]
            if side == "decoder":
                attns.append(("encoder_attn", "encoder_attn"))
            for ours_attn, hf_attn in attns:
                for proj in attn_mods:
                    plan[f"{ours}.{ours_attn}.{proj}.weight"] = (
                        f"{hf}.{hf_attn}.{proj}.weight", True)
                    plan[f"{ours}.{ours_attn}.{proj}.bias"] = (
                        f"{hf}.{hf_attn}.{proj}.bias", False)
                plan[f"{ours}.{ours_attn}_layer_norm.weight"] = (
                    f"{hf}.{hf_attn}_layer_norm.weight", False)
                plan[f"{ours}.{ours_attn}_layer_norm.bias"] = (
                    f"{hf}.{hf_attn}_layer_norm.bias", False)
            for fc in ("fc1", "fc2"):
                plan[f"{ours}.{fc}.weight"] = (f"{hf}.{fc}.weight", True)
                plan[f"{ours}.{fc}.bias"] = (f"{hf}.{fc}.bias", False)
            plan[f"{ours}.final_layer_norm.weight"] = (
                f"{hf}.final_layer_norm.weight", False)
            plan[f"{ours}.final_layer_norm.bias"] = (
                f"{hf}.final_layer_norm.bias", False)

    mapped, consumed = {}, set()
    for name, (hf_key, transpose) in plan.items():
        if hf_key not in state:
            raise KeyError(f"bart_from_hf: checkpoint is missing {hf_key!r}")
        v = _hf_to_np(state[hf_key])
        if name == "final_logits_bias":
            v = v.reshape(-1)          # HF stores [1, vocab]
        mapped[name] = v.T if transpose else v
        consumed.add(hf_key)
    leftovers = [k for k in state
                 if k not in consumed and k != "lm_head.weight"
                 and "embed_tokens" not in k]   # encoder/decoder aliases
    if leftovers:
        raise ValueError(
            f"bart_from_hf: checkpoint tensors this model cannot represent: "
            f"{leftovers[:5]}{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected
    if missing:
        raise KeyError(f"bart_from_hf: model keys not covered: {missing[:5]}")
    return model
