"""OLMo2 decoder family (AI2's OLMo-2 line, 1B → 32B).

The Llama trunk with two structural deviations, both trunk-level:

- POST-norm-only blocks: ``h = h + norm(attn(h)); h = h + norm(mlp(h))``
  — no input/pre norms (own decoder layer via the ``_make_decoder_layer``
  hook; the final stack norm stays);
- ``qk_norm="full"``: ONE RMSNorm over the whole projected q (and k)
  width, applied before the head split (Qwen3's variant norms per head
  after the split).

Everything else is the Llama recipe (SwiGLU, full RoPE, untied head), so
caches, serving, beams, LoRA and the engine all apply unchanged.
``olmo2_from_hf`` converts transformers checkpoints — the key layout is
Llama's with the post-only norm pair.
"""
from __future__ import annotations

import dataclasses

from ..nn.layer import Layer
from .llama import (LlamaAttention, LlamaConfig, LlamaForCausalLM, LlamaMLP,
                    LlamaModel, LlamaRMSNorm, _from_hf, layer_window)

_OLMO2_NORMS = ("post_attention_layernorm", "post_feedforward_layernorm")


@dataclasses.dataclass
class Olmo2Config(LlamaConfig):
    # OLMo-2-7B shape
    vocab_size: int = 100352
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 500000.0
    qk_norm: "bool | str" = "full"

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32")
        base.update(kw)
        return Olmo2Config(**base)


class Olmo2DecoderLayer(Layer):
    """Post-norm block: the sublayer OUTPUT is normed, then residual-added
    — no pre-norms at all."""

    def __init__(self, config: Olmo2Config):
        super().__init__(dtype=config.dtype)
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)
        self.post_feedforward_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden_states, cos, sin, attention_mask=None,
                kv_cache=None):
        if kv_cache is not None:
            a, kv_cache = self.self_attn(hidden_states, cos, sin,
                                         attention_mask, kv_cache)
        else:
            a = self.self_attn(hidden_states, cos, sin, attention_mask)
        hidden_states = hidden_states + self.post_attention_layernorm(a)
        hidden_states = hidden_states + self.post_feedforward_layernorm(
            self.mlp(hidden_states))
        if kv_cache is not None:
            return hidden_states, kv_cache
        return hidden_states


class Olmo2Model(LlamaModel):
    @staticmethod
    def _make_decoder_layer(config, layer_idx):
        layer = Olmo2DecoderLayer(config)
        layer.self_attn.window = layer_window(config, layer_idx)
        return layer


class Olmo2ForCausalLM(LlamaForCausalLM):
    """OLMo2 causal LM — post-norm trunk + full-width q/k norms."""

    model_cls = Olmo2Model

    def __init__(self, config: Olmo2Config):
        if config.qk_norm != "full":
            raise ValueError("OLMo2 norms the WHOLE projected q/k "
                             "(qk_norm='full')")
        super().__init__(config)


def olmo2_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build an Olmo2ForCausalLM from a transformers Olmo2 model (or a
    raw state dict + config)."""
    config_overrides.setdefault("qk_norm", "full")
    return _from_hf(Olmo2Config, Olmo2ForCausalLM, hf_model_or_state,
                    hf_config, layer_norms=_OLMO2_NORMS,
                    **config_overrides)
