"""DeepSeek-V2/V3 causal LM — Multi-head Latent Attention (MLA) + DeepSeekMoE.

Reference anchors: BASELINE.json names DeepSeekMoE as a target workload and
the reference serves this family through its fused MoE machinery
(paddle/phi/kernels/fusion/cutlass/fused_moe_kernel.cu, the
moe_gate_dispatch SPMD rule paddle/phi/infermeta/spmd_rules/
moe_gate_dispatch.cc); the MLA block itself follows the DeepSeek-V2
technical report (arXiv:2405.04434) and the public HF
``modeling_deepseek.DeepseekV2Attention`` semantics.

MLA in one paragraph: instead of per-head K/V projections, the layer
projects the hidden state to a small shared latent ``c_kv``
(``kv_lora_rank``, e.g. 512) plus one shared RoPE key ``k_pe``
(``qk_rope_head_dim``, e.g. 64, MQA-style — one head, broadcast to all
query heads). Per-head keys/values are re-expanded from the latent with
``kv_b_proj`` (no position information — RoPE rides only the decoupled
``k_pe`` slice). Queries are optionally low-rank too (``q_lora_rank``).

TPU-native design — two execution regimes:

- **Training / prefill (expanded)**: re-expand K/V from the latent and run
  ordinary causal attention; the q/k head dim is
  ``qk_nope_head_dim + qk_rope_head_dim`` (192 at DeepSeek shapes). On TPU
  the GQA splash kernel takes the hop with q/k/v zero-padded to the next
  128 lane multiple (exact: zero columns add nothing to the dots, the true
  ``sm_scale`` is passed explicitly, and the value padding is sliced off).
  Everything is batched matmuls — MXU-shaped, GSPMD-shardable over mp.
- **Decode (absorbed)**: the KV cache stores ONLY ``c_kv`` + ``k_pe`` —
  ``kv_lora_rank + qk_rope_head_dim`` floats per token (576 at DeepSeek
  shapes vs 2048 for 8-head GQA at d=128: a 3.5x cache/bandwidth cut, the
  reason MLA exists). Scores never materialize per-head keys: q_nope is
  absorbed through the K half of ``kv_b_proj`` once per step
  (``q_lat = q_nope · W_uk``), scores = ``q_lat · c_kv + q_pe · k_pe``,
  and the context is read back through the V half
  (``out = (probs · c_kv) · W_uv``). The buffer einsums stream the latent
  once — decode is HBM-bound on 576 bytes/token/layer instead of 2 KiB.

The MoE FFN (routed + shared experts, grouped GEMM, EP-shardable) is the
shared ``MoEMLP`` from models/llama_moe.py; DeepSeek-V3 routing (sigmoid
affinities + aux-free correction bias + routed_scaling_factor) comes from
the same config knobs.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from .. import nn
from ..ops.registry import apply
from ..distributed.topology import get_hybrid_communicate_group
from .llama import (LlamaModel, LlamaRMSNorm, _make_linear, _width_norm)
from .llama_moe import (LlamaMoEConfig, LlamaMoEDecoderLayer,
                        LlamaMoEForCausalLM)


@dataclasses.dataclass
class DeepseekV2Config(LlamaMoEConfig):
    """MLA dims on top of the DeepSeekMoE base (HF DeepseekV2Config names)."""

    q_lora_rank: int | None = None         # None → full-rank q_proj (V2-Lite)
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # DeepSeek-V3 multi-token prediction: D extra sequential modules, each
    # predicting token t+1+k from [RMSNorm(h_prev) ‖ RMSNorm(emb(t+k))]
    # through a fusion projection + one decoder block, sharing the main
    # embedding and lm head (arXiv:2412.19437 §2.2). Training-objective
    # only: forward(labels=...) adds mtp_loss_lambda x the mean MTP CE.
    num_nextn_predict_layers: int = 0
    mtp_loss_lambda: float = 0.3

    @staticmethod
    def tiny_mla(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=3, num_attention_heads=4,
                    num_key_value_heads=4, max_position_embeddings=256,
                    dtype="float32", n_routed_experts=4,
                    num_experts_per_tok=2, moe_intermediate_size=64,
                    first_k_dense_replace=1, kv_lora_rank=32,
                    qk_nope_head_dim=32, qk_rope_head_dim=16,
                    v_head_dim=32, q_lora_rank=None)
        base.update(kw)
        return DeepseekV2Config(**base)

    @staticmethod
    def tiny_v3(**kw):
        """V3-style routing on the tiny shape: sigmoid scores + aux-free
        correction bias + group-limited selection + routed scaling."""
        base = dict(moe_scoring_func="sigmoid", moe_correction_bias=True,
                    routed_scaling_factor=2.5, router_aux_loss_coef=0.0,
                    n_group=2, topk_group=1)
        base.update(kw)
        return DeepseekV2Config.tiny_mla(**base)


def _pad_lanes(x, to: int):
    """Zero-pad the last dim up to ``to`` (a 128 multiple for the MXU)."""
    d = x.shape[-1]
    if d == to:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, to - d)])


def mla_softmax_scale(cfg):
    """1/sqrt(d_qk) — times the yarn mscale_all_dim factor SQUARED when the
    checkpoint scales softmax (HF DeepseekV2Attention under yarn:
    ``softmax_scale *= yarn_get_mscale(factor, mscale_all_dim)**2``)."""
    from .llama import _rope_type, _yarn_get_mscale

    scale = 1.0 / math.sqrt(cfg.qk_nope_head_dim + cfg.qk_rope_head_dim)
    rs = cfg.rope_scaling
    if _rope_type(rs) == "yarn":
        mad = float(rs.get("mscale_all_dim", 0) or 0)
        if mad:
            m = _yarn_get_mscale(float(rs["factor"]), mad)
            scale = scale * m * m
    return scale


def _mla_sdpa(q, k, v, *, causal: bool, use_flash: bool, scale: float):
    """Expanded-attention hop shared by training and prefill: q/k at
    ``qk_nope+qk_rope`` width, v at ``v_head_dim``. Takes the splash
    kernel with lane padding when the shapes tile; else the shared
    f32-softmax SDPA reference."""
    from ..nn.functional.attention import _sdpa_ref
    from ..ops.pallas import flash_attention as pf

    dv = v.shape[-1]
    if use_flash:
        dqk_p = -(-q.shape[-1] // 128) * 128
        dv_p = -(-dv // 128) * 128
        qp, kp = _pad_lanes(q, dqk_p), _pad_lanes(k, dqk_p)
        vp = _pad_lanes(v, dv_p)
        if pf.supported(qp, kp, vp):
            out = pf.flash_attention_bshd(qp, kp, vp, causal=causal,
                                          sm_scale=scale)
            return out[..., :dv].astype(q.dtype)
    return _sdpa_ref(q, k, v, causal=causal, scale=scale)


def _absorbed_tail(q_lat, q_pe, ckv_buf, kpe_buf, w_uv, scale, dr, mask,
                   kernel_pos, allowed, use_flash, interpret):
    """The absorbed-attention tail shared by the generate() cache path and
    the serving engine: optional S=1 Pallas hop (single pass over the
    latent buffer), else masked-softmax einsums. q_lat [B,S,H,r] f32
    UNscaled; q_pe [B,S,H,dr] roped; mask [B or 1, 1, S, T] bool;
    kernel_pos scalar or [B] row limits for the kernel. Returns the
    latent-absorbed output [B,S,H,dv] (f32)."""
    S = q_lat.shape[1]
    if S == 1 and use_flash:
        from ..ops.pallas import mla_decode as pmd

        ql = q_lat[:, 0] * scale
        qp = q_pe[:, 0].astype(jnp.float32) * scale
        if pmd.supported(ql, ckv_buf, kpe_buf, interpret=interpret):
            ctx = pmd.mla_decode_attention(ql, qp, ckv_buf, kpe_buf,
                                           kernel_pos, allowed=allowed,
                                           interpret=interpret)
            return jnp.einsum("bhr,rhd->bhd", ctx.astype(jnp.float32),
                              w_uv.astype(jnp.float32))[:, None]
    scores = (jnp.einsum("bshr,btr->bhst", q_lat,
                         ckv_buf.astype(jnp.float32))
              # [..., :dr]: the TPU cache is lane-padded (empty_cache_layer)
              + jnp.einsum("bshd,btd->bhst", q_pe.astype(jnp.float32),
                           kpe_buf[..., :dr].astype(jnp.float32))) * scale
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhst,btr->bshr", probs, ckv_buf.astype(jnp.float32))
    return jnp.einsum("bshr,rhd->bshd", ctx, w_uv.astype(jnp.float32))


def mla_cached_attention(q_nope, q_pe, c_kv, k_pe, cos, sin, ckv_buf,
                         kpe_buf, pos, w_kv_b, *, nope_dim, v_dim,
                         allowed=None, row_pos=None, prefill=False,
                         use_flash=False, interpret=False, sm_scale=None):
    """RoPE + latent-cache write + absorbed MLA attention against the
    compressed buffer (the decode analog of generation.cached_attention).

    q_nope [B,S,H,dn]; q_pe [B,S,H,dr]; c_kv [B,S,r] (already
    kv_a_layernormed); k_pe [B,S,dr] (pre-RoPE); cos/sin [>=max_len, dr];
    ckv_buf [B,Smax,r]; kpe_buf [B,Smax,dr]; pos = buffer write offset;
    w_kv_b [r, H*(dn+dv)]; allowed/row_pos as in cached_attention.
    Returns (out [B,S,H,dv], new_ckv_buf, new_kpe_buf).

    Static pos==0 prefills (the ``prefill`` marker) take the EXPANDED path
    — causal attention over just the S new tokens (flash-capable); every
    other step runs the absorbed form over the latent buffer, which is
    exact at any (pos, S) including chunked-prefill appends.
    """
    from ..generation import _rope_rows
    from ..ops.pallas.fused_norm import rope_ref

    B, S, H, dn = q_nope.shape
    dr = q_pe.shape[-1]
    r = c_kv.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(nope_dim + dr)
    pos = jnp.asarray(pos, jnp.int32)

    k_pe4 = k_pe[:, :, None, :]                            # [B,S,1,dr]
    if row_pos is None:
        cos_s = jax.lax.dynamic_slice_in_dim(cos, pos, S, 0)
        sin_s = jax.lax.dynamic_slice_in_dim(sin, pos, S, 0)
        q_pe = rope_ref(q_pe, cos_s, sin_s)
        k_pe4 = rope_ref(k_pe4, cos_s, sin_s)
    else:
        q_pe = _rope_rows(q_pe, cos, sin, row_pos)
        k_pe4 = _rope_rows(k_pe4, cos, sin, row_pos)
    k_pe = k_pe4[:, :, 0, :].astype(kpe_buf.dtype)

    ckv_buf = jax.lax.dynamic_update_slice(
        ckv_buf, c_kv.astype(ckv_buf.dtype), (0, pos, 0))
    kpe_buf = jax.lax.dynamic_update_slice(kpe_buf, k_pe, (0, pos, 0))

    w3 = w_kv_b.reshape(r, H, nope_dim + v_dim)
    if bool(prefill) and S > 1 and allowed is None and row_pos is None:
        # expanded prefill: re-inflate K/V for the S new tokens only (the
        # rest of the buffer is empty at pos==0)
        kv = jnp.einsum("bsr,rhd->bshd", c_kv.astype(w3.dtype), w3)
        k_nope, v = kv[..., :nope_dim], kv[..., nope_dim:]
        q = jnp.concatenate([q_nope, q_pe.astype(q_nope.dtype)], axis=-1)
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(k_pe4.astype(k_nope.dtype),
                                      (B, S, H, dr))], axis=-1)
        out = _mla_sdpa(q, k, v, causal=True, use_flash=use_flash,
                        scale=scale)
        return out, ckv_buf, kpe_buf

    # absorbed attention over the latent buffer (shared tail; Pallas
    # single-pass hop at S=1)
    w_uk, w_uv = w3[..., :nope_dim], w3[..., nope_dim:]
    q_lat = jnp.einsum("bshd,rhd->bshr", q_nope.astype(jnp.float32),
                       w_uk.astype(jnp.float32))
    T = ckv_buf.shape[1]
    t_idx = jnp.arange(T)
    valid = t_idx[None, :] <= (pos + jnp.arange(S))[:, None]   # [S, T]
    mask = valid[None, None]                                   # [1,1,S,T]
    if allowed is not None:
        mask = mask & allowed[:, None, None, :]                # [B,1,S,T]
    out = _absorbed_tail(q_lat, q_pe, ckv_buf, kpe_buf, w_uv, scale, dr,
                         mask, kernel_pos=pos, allowed=allowed,
                         use_flash=use_flash, interpret=interpret)
    return out.astype(q_nope.dtype), ckv_buf, kpe_buf


def mla_serving_attention(q_nope, q_pe, c_kv, k_pe, cos, sin, ckv_buf,
                          kpe_buf, lengths, w_kv_b, *, nope_dim, v_dim,
                          use_flash=False, interpret=False, sm_scale=None):
    """Continuous-batching decode over the latent cache: each SLOT row sits
    at its own length (requests admit/retire independently), so writes
    scatter per row at ``lengths[b]``, RoPE rides per-row positions, and
    attention masks ``t <= lengths[b]``. S must be 1 (one token per active
    slot per engine step). Returns (out [B,1,H,dv], new_ckv, new_kpe).

    The Pallas decode kernel takes the hop with per-row ``pos`` when the
    shapes tile; else the masked einsum. Rows whose slot is empty
    (length 0) compute one masked column of garbage that the engine
    discards — identical to the paged path's dead-slot behavior."""
    from ..generation import _rope_rows

    B, S, H, dn = q_nope.shape
    if S != 1:
        raise ValueError(f"mla_serving_attention decodes one token per "
                         f"slot per step, got S={S}")
    dr = q_pe.shape[-1]
    r = c_kv.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(nope_dim + dr)
    lengths = jnp.asarray(lengths, jnp.int32)

    q_pe = _rope_rows(q_pe, cos, sin, lengths)
    k_pe4 = _rope_rows(k_pe[:, :, None, :], cos, sin, lengths)

    rows = jnp.arange(B)
    ckv_buf = ckv_buf.at[rows, lengths].set(
        c_kv[:, 0].astype(ckv_buf.dtype))
    kpe_buf = kpe_buf.at[rows, lengths, :dr].set(
        k_pe4[:, 0, 0, :].astype(kpe_buf.dtype))

    w3 = w_kv_b.reshape(r, H, nope_dim + v_dim)
    w_uk, w_uv = w3[..., :nope_dim], w3[..., nope_dim:]
    q_lat = jnp.einsum("bshd,rhd->bshr", q_nope.astype(jnp.float32),
                       w_uk.astype(jnp.float32))
    T = ckv_buf.shape[1]
    mask = (jnp.arange(T)[None, :] <= lengths[:, None])[:, None, None]
    out = _absorbed_tail(q_lat, q_pe, ckv_buf, kpe_buf, w_uv, scale, dr,
                         mask, kernel_pos=lengths, allowed=None,
                         use_flash=use_flash, interpret=interpret)
    return out.astype(q_nope.dtype), ckv_buf, kpe_buf


class DeepseekV2Attention(Layer):
    """MLA block: low-rank q (optional), shared compressed kv latent +
    decoupled MQA RoPE key, per-head re-expansion."""

    def __init__(self, config: DeepseekV2Config):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.config = config
        h = config.hidden_size
        H = config.num_attention_heads
        dn, dr = config.qk_nope_head_dim, config.qk_rope_head_dim
        dv, r = config.v_head_dim, config.kv_lora_rank
        self.num_heads, self.nope_dim, self.rope_dim, self.v_dim = H, dn, dr, dv
        bias = config.attention_bias
        if config.q_lora_rank:
            with dtype_guard(config.dtype):
                self.q_a_proj = nn.Linear(h, config.q_lora_rank,
                                          bias_attr=None if bias else False)
            self.q_a_layernorm = _width_norm(config, config.q_lora_rank)
            self.q_b_proj = _make_linear(config.q_lora_rank, H * (dn + dr),
                                         column=True, config=config)
            self.q_proj = None
        else:
            self.q_proj = _make_linear(h, H * (dn + dr), column=True,
                                       config=config, has_bias=bias)
        # latent projection stays replicated (it is the SHARED cache the
        # absorbed path streams; r+dr doesn't shard over heads)
        with dtype_guard(config.dtype):
            self.kv_a_proj_with_mqa = nn.Linear(
                h, r + dr, bias_attr=None if bias else False)
        self.kv_a_layernorm = _width_norm(config, r)
        self.kv_b_proj = _make_linear(r, H * (dn + dv), column=True,
                                      config=config)
        self.o_proj = _make_linear(H * dv, h, column=False, config=config)
        self.softmax_scale = mla_softmax_scale(config)

    def _kv_b_weight(self):
        """kv_b_proj's weight for the absorbed/expansion contractions —
        through the adapter-folded view when the layer is LoRA-wrapped
        (reading .weight directly would silently bypass the adapter)."""
        lin = self.kv_b_proj
        if hasattr(lin, "effective_weight"):
            return lin.effective_weight()
        return lin.weight

    def _project(self, hidden_states):
        """Shared q/latent projections → (q_nope, q_pe, c_kv, k_pe)."""
        b, s = hidden_states.shape[0], hidden_states.shape[1]
        H, dn, dr = self.num_heads, self.nope_dim, self.rope_dim
        if self.q_proj is not None:
            q = self.q_proj(hidden_states)
        else:
            q = self.q_b_proj(self.q_a_layernorm(self.q_a_proj(hidden_states)))
        q = q.reshape([b, s, H, dn + dr])
        kv_a = self.kv_a_proj_with_mqa(hidden_states)
        c_kv = self.kv_a_layernorm(kv_a[..., : self.config.kv_lora_rank])
        k_pe = kv_a[..., self.config.kv_lora_rank:]
        return q[..., :dn], q[..., dn:], c_kv, k_pe

    def forward(self, hidden_states, cos, sin, attention_mask=None,
                kv_cache=None, position_offset=0):
        b, s = hidden_states.shape[0], hidden_states.shape[1]
        H, dn, dr, dv = (self.num_heads, self.nope_dim, self.rope_dim,
                         self.v_dim)
        cfg = self.config
        q_nope, q_pe, c_kv, k_pe = self._project(hidden_states)

        if isinstance(kv_cache, dict) and "lengths" in kv_cache:
            # continuous-batching engine cache: per-row slot lengths
            out, ckv_buf, kpe_buf = apply(
                "mla_attention_serving", mla_serving_attention,
                q_nope, q_pe, c_kv, k_pe, cos, sin,
                kv_cache["c_kv"], kv_cache["k_pe"], kv_cache["lengths"],
                self._kv_b_weight(), nope_dim=dn, v_dim=dv,
                use_flash=cfg.use_flash_attention,
                sm_scale=self.softmax_scale)
            result = self.o_proj(out.reshape([b, s, H * dv]))
            new = {"c_kv": ckv_buf, "k_pe": kpe_buf,
                   "lengths": kv_cache["lengths"] + s}
            return result, new
        if isinstance(kv_cache, dict):
            out, ckv_buf, kpe_buf = apply(
                "mla_attention_cached", mla_cached_attention,
                q_nope, q_pe, c_kv, k_pe, cos, sin,
                kv_cache["c_kv"], kv_cache["k_pe"], kv_cache["pos"],
                self._kv_b_weight(),
                nope_dim=dn, v_dim=dv,
                allowed=kv_cache.get("allowed"),
                row_pos=kv_cache.get("row_pos"),
                prefill=bool(kv_cache.get("prefill", False)),
                use_flash=cfg.use_flash_attention,
                sm_scale=self.softmax_scale)
            result = self.o_proj(out.reshape([b, s, H * dv]))
            new = {"c_kv": ckv_buf, "k_pe": kpe_buf,
                   "pos": kv_cache["pos"] + s}
            if "allowed" in kv_cache:
                new["allowed"] = kv_cache["allowed"]
            if "row_pos" in kv_cache:
                new["row_pos"] = kv_cache["row_pos"] + s
            return result, new
        if kv_cache is not None:
            raise NotImplementedError(
                "MLA supports the dict (static-buffer) cache only — the "
                "tuple concat cache would store EXPANDED k/v and defeat "
                "the latent compression")

        def attn_fn(q_nope, q_pe, c_kv, k_pe, cos, sin, w_kv_b):
            from ..ops.pallas.fused_norm import rope_ref

            q_pe_r = rope_ref(q_pe, cos, sin).astype(q_nope.dtype)
            k_pe_r = rope_ref(k_pe[:, :, None, :], cos, sin)
            hcg = get_hybrid_communicate_group()
            sep = (hcg is not None and hcg.get_sep_parallel_world_size() > 1)
            if sep and cfg.sep_mode == "ulysses":
                raise NotImplementedError(
                    "MLA context parallelism rides the latent ring; "
                    "Ulysses needs a per-head KV axis the latent doesn't "
                    "have — use sep_mode='ring'")
            if sep and cfg.sep_mode == "ring":
                # context parallelism: the ring rotates the COMPRESSED
                # latent (r+dr floats/token) and each hop re-expands K/V
                # locally — see mla_ring_attention. ("allgather" falls
                # through: GSPMD gathers the sequence for the dense path.)
                import functools

                from ..distributed.collective import shard_map
                from jax.sharding import PartitionSpec as P

                from ..distributed.context_parallel import (
                    cp_mesh_axes, mla_ring_attention)

                mesh, batch_ax, head_ax = cp_mesh_axes(hcg)
                q = jnp.concatenate([q_nope, q_pe_r], axis=-1)
                cp = shard_map(
                    functools.partial(
                        mla_ring_attention, axis_name="sep", nope_dim=dn,
                        v_dim=dv, sm_scale=self.softmax_scale),
                    mesh=mesh,
                    in_specs=(P(batch_ax, "sep", head_ax, None),
                              P(batch_ax, "sep", None),
                              P(batch_ax, "sep", None),
                              P(None, head_ax)),
                    out_specs=P(batch_ax, "sep", head_ax, None),
                    check_vma=False)
                out = cp(q, c_kv, k_pe_r[:, :, 0, :].astype(c_kv.dtype),
                         w_kv_b)
                return out.reshape(b, s, H * dv)
            kv = jnp.einsum("bsr,rhd->bshd", c_kv,
                            w_kv_b.reshape(cfg.kv_lora_rank, H, dn + dv))
            k_nope, v = kv[..., :dn], kv[..., dn:]
            q = jnp.concatenate([q_nope, q_pe_r], axis=-1)
            k = jnp.concatenate(
                [k_nope,
                 jnp.broadcast_to(k_pe_r.astype(k_nope.dtype),
                                  (b, s, H, dr))], axis=-1)
            out = _mla_sdpa(q, k, v, causal=True,
                            use_flash=cfg.use_flash_attention,
                            scale=self.softmax_scale)
            return out.reshape(b, s, H * dv)

        out = apply("mla_attention", attn_fn, q_nope, q_pe, c_kv, k_pe,
                    cos, sin, self._kv_b_weight())
        return self.o_proj(out)


class DeepseekV2DecoderLayer(LlamaMoEDecoderLayer):
    """MLA attention + (dense | DeepSeekMoE) FFN — the shared MoE decoder
    block with the attention class swapped."""

    attn_cls = DeepseekV2Attention


class DeepseekV2Model(LlamaModel):
    """LlamaModel trunk with MLA decoder layers and qk_rope_head_dim RoPE
    tables; the decode cache is the compressed latent (see
    ``empty_cache_layer``)."""

    def __init__(self, config: DeepseekV2Config):
        base_cfg = dataclasses.replace(config, num_hidden_layers=0,
                                       layer_types=None)
        super().__init__(base_cfg)
        self.config = config
        # NOT RecomputeLayer-wrapped (matches LlamaMoEModel): the aux-loss
        # walk reads layer.is_moe / layer.mlp._aux_loss directly
        self.layers = nn.LayerList(
            [DeepseekV2DecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])

    def _rope_dim(self):
        # RoPE rides ONLY the decoupled qk_rope_head_dim slice (MLA)
        return self.config.qk_rope_head_dim

    def empty_cache_layer(self, batch, max_len, dtype):
        """Per-layer decode cache: the COMPRESSED latent + shared RoPE key
        (generation._empty_caches consumes this hook) —
        kv_lora_rank + qk_rope_head_dim floats per token.

        On TPU the k_pe buffer is allocated LANE-PADDED (width up to the
        next 128 multiple, zeros beyond qk_rope_head_dim) so the Pallas
        decode kernel consumes it zero-copy every step; writers write the
        true width at offset 0 and einsum readers slice it back."""
        cfg = self.config
        dr = cfg.qk_rope_head_dim
        try:
            if jax.default_backend() == "tpu":
                dr = -(-dr // 128) * 128
        except RuntimeError:  # pragma: no cover - backend init failed: the un-padded width is correct on every non-TPU path
            pass
        return {"c_kv": jnp.zeros((batch, max_len, cfg.kv_lora_rank), dtype),
                "k_pe": jnp.zeros((batch, max_len, dr), dtype)}


class DeepseekMTPLayer(Layer):
    """One DeepSeek-V3 multi-token-prediction depth (arXiv:2412.19437
    §2.2): fuse ``[RMSNorm(h_prev) ‖ RMSNorm(emb(t_shifted))]`` through a
    2h→h projection, then one full (MLA + MoE/dense) decoder block. The
    main model's embedding and lm head are SHARED — this module owns only
    the two input norms, the fusion projection, the block, and the
    pre-head norm. RoPE inside the block uses 0-based tables for the
    shifted window — exact, since RoPE attention is relative."""

    def __init__(self, config: DeepseekV2Config, layer_idx: int):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.hnorm = LlamaRMSNorm(config)
        self.enorm = LlamaRMSNorm(config)
        with dtype_guard(config.dtype):
            self.eh_proj = nn.Linear(2 * config.hidden_size,
                                     config.hidden_size, bias_attr=False)
        self.block = DeepseekV2DecoderLayer(config, layer_idx)
        self.norm = LlamaRMSNorm(config)

    def fuse(self, h_prev, emb_next):
        """[RMSNorm(h_prev) ‖ RMSNorm(emb_next)] → 2h→h projection — the
        block input, shared by training and the speculative draft path."""
        x = apply("mtp_fuse",
                  lambda a, b: jnp.concatenate([a, b], axis=-1),
                  self.hnorm(h_prev), self.enorm(emb_next))
        return self.eh_proj(x)

    def forward(self, h_prev, emb_next, cos, sin):
        return self.block(self.fuse(h_prev, emb_next), cos, sin)


class DeepseekV2ForCausalLM(LlamaMoEForCausalLM):
    """DeepSeek-V2/V3 causal LM: MLA + MoE, latent-cache generate(); the
    aux-loss plumbing (router_aux_loss_coef) comes from the MoE base.

    ``num_nextn_predict_layers = D > 0`` adds the V3 multi-token-prediction
    chain: depth k predicts token t+1+k through its own fused block over
    the previous depth's hidden, sharing the embedding/head; training loss
    becomes ``L_main + mtp_loss_lambda · mean_k(L_k)``. Inference paths
    (generate/serving/speculative) ignore the MTP modules entirely."""

    model_cls = DeepseekV2Model

    def __init__(self, config: DeepseekV2Config):
        super().__init__(config)
        D = int(config.num_nextn_predict_layers)
        self.mtp_layers = (nn.LayerList(
            [DeepseekMTPLayer(config, config.num_hidden_layers + k)
             for k in range(D)]) if D else None)

    def forward(self, input_ids, labels=None, attention_mask=None):
        D = int(self.config.num_nextn_predict_layers)
        if labels is None or not D:
            return super().forward(input_ids, labels=labels,
                                   attention_mask=attention_mask)
        if self.config.fuse_linear_cross_entropy:
            raise NotImplementedError(
                "multi-token prediction computes explicit logits per "
                "depth; unset fuse_linear_cross_entropy to train with "
                "num_nextn_predict_layers > 0")
        from .llama import causal_lm_loss

        S = input_ids.shape[1]
        if D >= S:
            raise ValueError(
                f"num_nextn_predict_layers {D} needs sequences longer "
                f"than {D} tokens, got {S}")
        normed, pre = self.llama(input_ids, attention_mask,
                                 return_prenorm=True)
        loss = causal_lm_loss(self.lm_head_logits(normed), labels)
        # MTP chain: depth k (1-based) pairs the PRE-norm h_{k-1}[:, i]
        # with emb(t_{i+k}) and targets labels[:, i+k] (= t_{i+k+1}).
        # Like the trunk's training path, the blocks are causal-only —
        # pad positions are excluded through the label ignore mask, not an
        # attention mask. Embedding and RoPE tables are computed once at
        # full length and sliced per depth.
        emb_full = self.llama.embed_tokens(input_ids).astype(
            self.config.dtype)
        cos_full, sin_full = self.llama._rope(S)
        h_prev = pre
        mtp_total = None
        for k, layer in enumerate(self.mtp_layers, start=1):
            L_k = S - k
            h_prev = layer(h_prev[:, :L_k], emb_full[:, k:],
                           cos_full[:L_k], sin_full[:L_k])
            logits_k = self.lm_head_logits(layer.norm(h_prev))
            l_k = causal_lm_loss(logits_k, labels[:, k:])
            mtp_total = l_k if mtp_total is None else mtp_total + l_k
        loss = loss + self.config.mtp_loss_lambda * (mtp_total / D)
        # router aux AFTER the chain so the MTP blocks' MoE routers get
        # load-balancing gradient too (mean over every MoE layer that ran)
        aux = self.aux_loss(
            extra_layers=[layer.block for layer in self.mtp_layers])
        if aux is not None:
            loss = loss + self.config.router_aux_loss_coef * aux
        return loss, None


# ---------------------------------------------------------------------------
# pipeline-parallel DeepSeek (MLA + MoE under pp — the way the V2/V3
# recipes actually train: pp x ep x mp)
# ---------------------------------------------------------------------------

from .llama import LlamaDecoderLayerPipe, LlamaForCausalLMPipe  # noqa: E402


class DeepseekDecoderLayerPipe(LlamaDecoderLayerPipe):
    """One MLA(+MoE) decoder layer as a pipeline item — the shared pipe
    item with the decoder class and RoPE width (the decoupled
    qk_rope_head_dim slice) swapped."""

    decoder_cls = DeepseekV2DecoderLayer

    def _rope_dim(self):
        return self.config.qk_rope_head_dim


class DeepseekForCausalLMPipe(LlamaForCausalLMPipe):
    """Stage-partitioned DeepSeek-V2/V3 causal LM — the shared pipe
    assembly with MLA+MoE decoder layers. Train with
    ``fleet.distributed_model`` under pp_degree > 1, then
    ``pp.train_batch([ids, labels], opt)``.

    The pipeline loss is the stage-local LM loss, so the router aux term
    cannot be accumulated across stages — use aux-free balancing
    (``moe_correction_bias``, the V3 recipe) or set
    ``router_aux_loss_coef=0``; a nonzero coef raises rather than being
    silently dropped."""

    decoder_pipe_cls = DeepseekDecoderLayerPipe
    shared_embed_key = "deepseek_embed"

    def _decoder_args(self, config, layer_idx):
        return (config, layer_idx)  # first_k_dense_replace needs the index

    def _check_config(self, config):
        super()._check_config(config)
        has_moe = config.first_k_dense_replace < config.num_hidden_layers
        if has_moe and config.router_aux_loss_coef:
            raise NotImplementedError(
                "the pipeline loss cannot carry the cross-stage router aux "
                "term; use aux-free balancing (moe_correction_bias) or "
                "router_aux_loss_coef=0")
        if config.num_nextn_predict_layers:
            raise NotImplementedError(
                "multi-token prediction is a monolithic-model training "
                "objective; set num_nextn_predict_layers=0 for the "
                "pipeline layout")


def deepseek_from_hf(hf_model, config=None):
    """Convert a transformers ``DeepseekV2ForCausalLM``-style state dict.

    The HF checkpoint stores the RoPE slices (q_pe rows, the k_pe tail of
    kv_a_proj_with_mqa) in INTERLEAVED pair layout; this build's rope_ref
    uses the half-split rotate_half layout, so those output rows are
    permuted even→first-half, odd→second-half (the same de-interleave the
    ernie45 loader does).
    """
    import numpy as np

    sd = {k: np.asarray(v.detach().cpu().float().numpy())
          for k, v in hf_model.state_dict().items()}
    hc = hf_model.config
    if config is None:
        moe_layers = getattr(hc, "n_routed_experts", None) is not None
        config = DeepseekV2Config(
            vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
            intermediate_size=hc.intermediate_size,
            num_hidden_layers=hc.num_hidden_layers,
            num_attention_heads=hc.num_attention_heads,
            num_key_value_heads=hc.num_attention_heads,
            max_position_embeddings=hc.max_position_embeddings,
            rms_norm_eps=hc.rms_norm_eps, rope_theta=hc.rope_theta,
            rope_scaling=(dict(hc.rope_scaling)
                          if getattr(hc, "rope_scaling", None) else None),
            dtype="float32",
            q_lora_rank=getattr(hc, "q_lora_rank", None),
            kv_lora_rank=hc.kv_lora_rank,
            qk_nope_head_dim=hc.qk_nope_head_dim,
            qk_rope_head_dim=hc.qk_rope_head_dim,
            v_head_dim=hc.v_head_dim,
            n_routed_experts=(hc.n_routed_experts if moe_layers else 0),
            n_shared_experts=(getattr(hc, "n_shared_experts", 0) or 0),
            num_experts_per_tok=(hc.num_experts_per_tok if moe_layers else 2),
            moe_intermediate_size=getattr(hc, "moe_intermediate_size", 1408),
            first_k_dense_replace=(getattr(hc, "first_k_dense_replace", 0)
                                   if moe_layers else 10 ** 9),
            norm_topk_prob=bool(getattr(hc, "norm_topk_prob", False)),
            routed_scaling_factor=float(
                getattr(hc, "routed_scaling_factor", 1.0)),
            moe_scoring_func=str(getattr(hc, "scoring_func", "softmax")),
            moe_correction_bias=(getattr(hc, "topk_method", "")
                                 == "noaux_tc"),
            # group-limited routing (V2 group_limited_greedy / V3 noaux_tc)
            n_group=int(getattr(hc, "n_group", 1) or 1),
            topk_group=int(getattr(hc, "topk_group", 1) or 1),
            # aux-free checkpoints (noaux_tc) carry aux_loss_alpha=0; the
            # HF field is the authority, NOT this build's 0.001 default
            router_aux_loss_coef=float(
                getattr(hc, "aux_loss_alpha", 0.0) or 0.0),
            tie_word_embeddings=bool(getattr(hc, "tie_word_embeddings",
                                             False)))
    # fail at CONVERT time on unsupported/malformed rope_scaling rather
    # than lazily at the first forward (yarn parameter errors included)
    from .llama import validate_rope_scaling

    validate_rope_scaling(config.rope_scaling,
                          max_position=config.max_position_embeddings)
    model = DeepseekV2ForCausalLM(config)
    H, dn, dr = (config.num_attention_heads, config.qk_nope_head_dim,
                 config.qk_rope_head_dim)
    r = config.kv_lora_rank

    def deinterleave_rows(w, dim):
        """Permute the trailing ``dim`` output rows of a [out, in] weight
        from interleaved (x0,y0,x1,y1,...) to half-split (x...,y...)."""
        head, tail = w[:-dim], w[-dim:]
        tail = tail.reshape(dim // 2, 2, -1)
        tail = np.concatenate([tail[:, 0], tail[:, 1]], axis=0)
        return np.concatenate([head, tail], axis=0)

    def deinterleave_q(w):
        """Same permutation on each head's q_pe tail rows of a q/q_b
        projection [H*(dn+dr), in]; transpose(0,2,1,3) groups
        evens-then-odds (half-split layout)."""
        w = w.reshape(H, dn + dr, -1)
        w = np.concatenate(
            [w[:, :dn],
             w[:, dn:].reshape(H, dr // 2, 2, -1).transpose(0, 2, 1, 3)
             .reshape(H, dr, -1)], axis=1)
        return w.reshape(H * (dn + dr), -1)

    def set_(layer, value, transpose=True):
        arr = value.T if transpose else value
        layer.weight._array = jnp.asarray(arr).astype(layer.weight.dtype)

    m = model.llama
    m.embed_tokens.weight._array = jnp.asarray(
        sd.pop("model.embed_tokens.weight")).astype(
            m.embed_tokens.weight.dtype)
    m.norm.weight._array = jnp.asarray(sd.pop("model.norm.weight")).astype(
        m.norm.weight.dtype)
    if model.lm_head is not None:
        set_(model.lm_head, sd.pop("lm_head.weight"))
    for i, layer in enumerate(m.layers):
        layer = getattr(layer, "inner", layer)
        p = f"model.layers.{i}"
        attn = layer.self_attn
        if attn.q_proj is not None:
            set_(attn.q_proj,
                 deinterleave_q(sd.pop(f"{p}.self_attn.q_proj.weight")))
        else:
            set_(attn.q_a_proj, sd.pop(f"{p}.self_attn.q_a_proj.weight"))
            attn.q_a_layernorm.weight._array = jnp.asarray(
                sd.pop(f"{p}.self_attn.q_a_layernorm.weight")).astype(
                    attn.q_a_layernorm.weight.dtype)
            set_(attn.q_b_proj,
                 deinterleave_q(sd.pop(f"{p}.self_attn.q_b_proj.weight")))
        w = sd.pop(f"{p}.self_attn.kv_a_proj_with_mqa.weight")
        set_(attn.kv_a_proj_with_mqa, deinterleave_rows(w, dr))
        attn.kv_a_layernorm.weight._array = jnp.asarray(
            sd.pop(f"{p}.self_attn.kv_a_layernorm.weight")).astype(
                attn.kv_a_layernorm.weight.dtype)
        set_(attn.kv_b_proj, sd.pop(f"{p}.self_attn.kv_b_proj.weight"))
        set_(attn.o_proj, sd.pop(f"{p}.self_attn.o_proj.weight"))
        layer.input_layernorm.weight._array = jnp.asarray(
            sd.pop(f"{p}.input_layernorm.weight")).astype(
                layer.input_layernorm.weight.dtype)
        layer.post_attention_layernorm.weight._array = jnp.asarray(
            sd.pop(f"{p}.post_attention_layernorm.weight")).astype(
                layer.post_attention_layernorm.weight.dtype)
        if layer.is_moe:
            from .llama_moe import pack_hf_experts

            mlp = layer.mlp
            mlp.gate_weight._array = jnp.asarray(
                sd.pop(f"{p}.mlp.gate.weight").T).astype(
                    mlp.gate_weight.dtype)
            if mlp.e_score_correction_bias is not None:
                mlp.e_score_correction_bias._array = jnp.asarray(
                    sd.pop(f"{p}.mlp.gate.e_score_correction_bias")).astype(
                        mlp.e_score_correction_bias.dtype)

            def tk(name, transpose=False):
                w = sd.pop(name)
                return w.T if transpose else w

            w1, b1, w2, b2 = pack_hf_experts(
                tk, f"{p}.mlp", config.n_routed_experts, config.hidden_size)
            mlp.experts.w1._array = jnp.asarray(w1).astype(mlp.experts.w1.dtype)
            mlp.experts.w2._array = jnp.asarray(w2).astype(mlp.experts.w2.dtype)
            if mlp.shared_expert is not None:
                sp = f"{p}.mlp.shared_experts"
                set_(mlp.shared_expert.gate_proj,
                     sd.pop(f"{sp}.gate_proj.weight"))
                set_(mlp.shared_expert.up_proj, sd.pop(f"{sp}.up_proj.weight"))
                set_(mlp.shared_expert.down_proj,
                     sd.pop(f"{sp}.down_proj.weight"))
        else:
            set_(layer.mlp.gate_proj, sd.pop(f"{p}.mlp.gate_proj.weight"))
            set_(layer.mlp.up_proj, sd.pop(f"{p}.mlp.up_proj.weight"))
            set_(layer.mlp.down_proj, sd.pop(f"{p}.mlp.down_proj.weight"))
    return model
