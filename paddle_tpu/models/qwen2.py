"""Qwen2 / Qwen2.5 decoder family.

Role parity: the reference serves Qwen2 through PaddleNLP's qwen2 modeling
(same decoder recipe as its llama modeling with q/k/v projection biases and
an optional sliding window). This build expresses Qwen2 as a LlamaConfig
specialization — the architecture differs from Llama-3 only in
``attention_bias=True``, the RoPE base, and the (optional) sliding window —
so every path (training, hybrid parallel, serving, HF interop) is the
already-tested Llama machinery.
"""
from __future__ import annotations

import dataclasses

from .llama import LlamaConfig, LlamaForCausalLM, _from_hf


@dataclasses.dataclass
class Qwen2Config(LlamaConfig):
    vocab_size: int = 151936
    hidden_size: int = 3584
    intermediate_size: int = 18944
    num_hidden_layers: int = 28
    num_attention_heads: int = 28
    num_key_value_heads: int = 4
    max_position_embeddings: int = 32768
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6
    attention_bias: bool = True          # the Qwen2 signature deviation

    @staticmethod
    def qwen25_7b(**kw):
        return Qwen2Config(**kw)

    @staticmethod
    def qwen25_0_5b(**kw):
        base = dict(hidden_size=896, intermediate_size=4864,
                    num_hidden_layers=24, num_attention_heads=14,
                    num_key_value_heads=2, tie_word_embeddings=True)
        base.update(kw)
        return Qwen2Config(**base)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32")
        base.update(kw)
        return Qwen2Config(**base)


class Qwen2ForCausalLM(LlamaForCausalLM):
    """Qwen2 causal LM — Llama decoder with q/k/v biases."""

    def __init__(self, config: Qwen2Config):
        if not config.attention_bias:
            raise ValueError("Qwen2 uses attention_bias=True")
        super().__init__(config)


def qwen2_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a Qwen2ForCausalLM from a transformers Qwen2 model (or a raw
    state dict + config)."""
    return _from_hf(Qwen2Config, Qwen2ForCausalLM, hf_model_or_state,
                    hf_config, **config_overrides)
