"""Llama-3 model family — the flagship pretraining workload.

Reference parity: the reference trains Llama via PaddleNLP on the fleet
hybrid-parallel stack (SURVEY §2.7, CS4); this is the equivalent model
implemented on paddle_tpu's layer system with TPU-first choices:

- GQA attention with a fused Pallas flash kernel (ops/pallas/flash_attention)
  and fused rotary embeddings (ops/pallas/fused_norm.fused_rope);
- RMSNorm via the fused Pallas kernel;
- tensor/sequence parallelism via the mp/sep axes of the hybrid mesh
  (parallel layers + sharding constraints), FSDP via the sharding axis;
- bf16 weights with f32 master copies in the optimizer (framework default).

Config names follow HF/PaddleNLP llama conventions so checkpoints map 1:1.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn.layer import Layer
from ..nn.initializer_core import Normal, Constant
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap
from ..distributed.topology import get_hybrid_communicate_group
from ..distributed import parallel_layers as mpu


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    # RoPE frequency scaling for long-context checkpoints: None, or a dict
    # like HF's rope_scaling — {"rope_type": "llama3", "factor": 8.0,
    # "low_freq_factor": 1.0, "high_freq_factor": 4.0,
    # "original_max_position_embeddings": 8192} (Llama-3.1/3.2), or
    # {"rope_type": "linear", "factor": N} (position interpolation)
    rope_scaling: Optional[dict] = None
    # bias on the q/k/v projections (Qwen2-style); o_proj stays bias-free
    attention_bias: bool = False
    # attention head width decoupled from hidden_size/num_heads (Qwen3:
    # e.g. hidden 2560, 32 heads, head_dim 128); None = the quotient
    head_dim: Optional[int] = None
    # RMSNorm on q/k after projection, before RoPE: False, True or
    # "per_head" (Qwen3 — one norm per head over head_dim), or "full"
    # (OLMo2 — one norm over the WHOLE projected width)
    qk_norm: "bool | str" = False
    # fraction of head_dim that rotates (GLM/StableLM/Phi-3-small class):
    # rope tables are built at rope_dim_of(config) width and the
    # application sites rotate only that leading slice
    partial_rotary_factor: float = 1.0
    # causal sliding-window attention (Mistral/Qwen2): each token attends
    # to at most the last `sliding_window` positions. The splash kernel
    # skips blocks outside the band (O(seq*window) work); dense fallbacks
    # apply the band mask.
    sliding_window: Optional[int] = None
    use_flash_attention: bool = True
    # attention strategy when the hybrid topology has sep_degree > 1:
    # "ring" (ppermute ring attention), "ulysses" (all-to-all head redistribution),
    # or "allgather" (let GSPMD gather k/v — the reference's SP-only behaviour)
    sep_mode: str = "ring"
    sequence_parallel: bool = False
    recompute: bool = False
    # MLP gating activation: "silu" (SwiGLU — Llama/Qwen/Mistral) or
    # "gelu_pytorch_tanh" (GeGLU — Gemma)
    hidden_act: str = "silu"
    # RMSNorm weight parameterized as (1 + w), zeros-init (Gemma): the
    # checkpoint stores the DELTA from identity, and norm output is
    # x_normed * (1 + w)
    rms_norm_offset: bool = False
    # multiply embedding output by sqrt(hidden_size) (Gemma input scaling)
    scale_embeddings: bool = False
    # attention softmax scale numerator (Gemma2): scale becomes
    # query_pre_attn_scalar**-0.5 instead of head_dim**-0.5. Implemented
    # by pre-scaling q after projection (RoPE is linear, so this is exact
    # on every attention path including the Pallas kernels)
    query_pre_attn_scalar: Optional[float] = None
    # tanh soft cap on attention logits (Gemma2): cap*tanh(scores/cap).
    # Flash falls back to the dense path; paged decode uses the exact
    # gather reference; CP refuses loudly
    attn_logit_softcapping: Optional[float] = None
    # tanh soft cap on the lm-head logits (Gemma2)
    final_logit_softcapping: Optional[float] = None
    # per-layer attention kind (Gemma2 alternation): tuple of
    # "sliding_attention"/"full_attention", one per layer — sliding layers
    # use ``sliding_window``, full layers ignore it. None = uniform.
    layer_types: Optional[tuple] = None
    # chunk the lm-head matmul + CE loss over token chunks (ops.fused_loss):
    # the [tokens, vocab] logits tensor never materializes — required to fit
    # large-vocab training shapes in one chip's HBM. forward(labels=...)
    # then returns (loss, None).
    fuse_linear_cross_entropy: bool = False
    dtype: str = "bfloat16"

    def __post_init__(self):
        if self.sep_mode not in ("ring", "ulysses", "allgather"):
            raise ValueError(
                f"sep_mode must be 'ring', 'ulysses' or 'allgather', got {self.sep_mode!r}")
        if self.hidden_act not in ("silu", "gelu_pytorch_tanh"):
            raise NotImplementedError(
                f"hidden_act must be 'silu' or 'gelu_pytorch_tanh', "
                f"got {self.hidden_act!r}")
        if self.final_logit_softcapping and self.fuse_linear_cross_entropy:
            raise NotImplementedError(
                "final_logit_softcapping cannot combine with "
                "fuse_linear_cross_entropy (the chunked-CE scan computes "
                "uncapped logits)")
        if self.qk_norm not in (False, True, "per_head", "full"):
            raise ValueError(
                f"qk_norm must be False, True, 'per_head' or 'full', "
                f"got {self.qk_norm!r}")
        if not (0.0 < self.partial_rotary_factor <= 1.0):
            raise ValueError(
                f"partial_rotary_factor must be in (0, 1], got "
                f"{self.partial_rotary_factor}")
        if self.layer_types is not None:
            self.layer_types = tuple(self.layer_types)
            if len(self.layer_types) != self.num_hidden_layers:
                raise ValueError(
                    f"layer_types has {len(self.layer_types)} entries for "
                    f"{self.num_hidden_layers} layers")
            bad = set(self.layer_types) - {"sliding_attention",
                                           "full_attention"}
            if bad:
                raise ValueError(f"unknown layer_types entries: {bad}")
            if ("sliding_attention" in self.layer_types
                    and self.sliding_window is None):
                raise ValueError(
                    "layer_types requests sliding_attention but "
                    "sliding_window is not set")

    @staticmethod
    def llama3_8b(**kw):
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_70b(**kw):
        base = dict(hidden_size=8192, intermediate_size=28672, num_hidden_layers=80,
                    num_attention_heads=64, num_key_value_heads=8)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                    max_position_embeddings=256, dtype="float32")
        base.update(kw)
        return LlamaConfig(**base)


def layer_window(config, layer_idx: int):
    """Layer ``layer_idx``'s sliding window: the uniform config value, or
    the per-layer schedule when ``layer_types`` is set (Gemma2 alternates
    sliding/full)."""
    lt = getattr(config, "layer_types", None)
    if not lt:
        return config.sliding_window
    return (config.sliding_window if lt[layer_idx] == "sliding_attention"
            else None)


def rope_dim_of(config) -> int:
    """Width of the rotary tables: head_dim scaled by
    partial_rotary_factor, floored to even (the rotate-half split)."""
    r = int(head_dim_of(config)
            * getattr(config, "partial_rotary_factor", 1.0))
    return r - (r % 2)


def head_dim_of(config) -> int:
    """Attention head width — ``config.head_dim`` when set (Qwen3 decouples
    it from hidden/heads), else the classic quotient. The ONE derivation
    shared by the attention layer, rope tables, cache allocators, and the
    serving engine."""
    hd = getattr(config, "head_dim", None)
    return int(hd) if hd else config.hidden_size // config.num_attention_heads


def _width_norm(config, width):
    """RMSNorm over an arbitrary trailing width (per-head q/k norms, the
    MLA low-rank latents) built from the family config."""
    sub = dataclasses.replace(config, hidden_size=width)
    return LlamaRMSNorm(sub)


SUPPORTED_ROPE_SCALING = ("llama3", "linear", "yarn", "longrope")


def _rope_type(scaling: Optional[dict]):
    """None/{} → "default"; a non-empty dict WITHOUT a type key returns
    None so downstream gates refuse it (silently treating a typed-less
    scaling dict as default would drop the checkpoint's scaling)."""
    if not scaling:
        return "default"
    return scaling.get("rope_type", scaling.get("type", None))


def _hf_get(hf_config):
    """Uniform accessor over a transformers config OBJECT or a raw dict —
    the one idiom every hf_config_to_* mapper needs."""
    return (hf_config.get if isinstance(hf_config, dict)
            else lambda k, d=None: getattr(hf_config, k, d))


def mapped_rope_scaling(get) -> Optional[dict]:
    """hf_config_to_* helper: read ``rope_scaling`` through the mapper's
    ``get``, validate it at CONVERT time, and return the dict (or None)
    ready for the config kwarg — the one guard shared by every family
    mapper."""
    scaling = get("rope_scaling")
    if scaling not in (None, {}):
        validate_rope_scaling(dict(scaling),
                              max_position=get("max_position_embeddings"))
    return dict(scaling) if scaling else None


def validate_rope_scaling(scaling: Optional[dict],
                          max_position: Optional[int] = None) -> None:
    """Checkpoint-loader gate: raise at CONVERT time both for rope_scaling
    TYPES this build can't reproduce (NotImplementedError) and for
    malformed configs of supported types (yarn parameter errors surface
    here instead of lazily at the first forward)."""
    rope_type = _rope_type(scaling)
    if rope_type in ("default", "none"):
        return
    if rope_type not in SUPPORTED_ROPE_SCALING:
        raise NotImplementedError(
            f"rope_scaling type {rope_type!r} is not implemented "
            f"(supported: {', '.join(sorted(SUPPORTED_ROPE_SCALING))})")
    if rope_type == "yarn":
        # dummy dims: only the parameter handling can raise
        _yarn_params(scaling, 64, 10000.0, fallback_orig=max_position)
    if rope_type == "longrope":
        n_short = len(scaling.get("short_factor") or ())
        n_long = len(scaling.get("long_factor") or ())
        if not n_short or not n_long or n_short != n_long:
            raise ValueError(
                "longrope rope_scaling needs short_factor and long_factor "
                f"lists of equal length (got {n_short}/{n_long})")
        if not (scaling.get("original_max_position_embeddings")
                or max_position):
            raise ValueError(
                "longrope rope_scaling needs "
                "original_max_position_embeddings (or a max_position "
                "fallback) to pick between the factor lists")


def _longrope_params(scaling: dict, dim: int, base: float, seq_len: int,
                     max_position: Optional[int] = None):
    """(inv_freq [dim//2], attention_factor) per transformers
    modeling_rope_utils._compute_longrope_parameters (Phi-3 LongRoPE):
    per-dim rescaled frequencies — the short_factor list within the
    pretrained window, the long_factor list beyond it — and a
    sqrt(1 + ln(f)/ln(orig)) magnitude factor on the tables.

    The factor list is chosen by the length the tables are BUILT for
    (static under jit). transformers switches on the runtime position
    instead, re-deriving frequencies mid-request when a cached generate
    crosses the pretrained window; a table built for the request's true
    maximum length applies the long factors from the start, which keeps
    every cached position self-consistent."""
    orig = int(scaling.get("original_max_position_embeddings")
               or max_position)
    factor = scaling.get("factor")
    if max_position and orig:
        factor = max_position / orig
    att = scaling.get("attention_factor")
    if att is None:
        att = (1.0 if not factor or factor <= 1.0
               else math.sqrt(1 + math.log(factor) / math.log(orig)))
    ext = (scaling["long_factor"] if seq_len > orig
           else scaling["short_factor"])
    ext = jnp.asarray(ext, jnp.float32)
    if ext.shape[0] != dim // 2:
        raise ValueError(
            f"longrope factor lists must have head_dim/2 = {dim // 2} "
            f"entries, got {ext.shape[0]}")
    inv_freq = 1.0 / (ext * base ** (
        jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    return inv_freq, float(att)


def _yarn_get_mscale(scale: float, m: float = 1.0) -> float:
    """yarn magnitude term (0.1·m·ln(s)+1) — shared by the table factor
    and the DeepSeek softmax mscale."""
    if scale <= 1:
        return 1.0
    return 0.1 * m * math.log(scale) + 1.0


def _yarn_params(scaling: dict, dim: int, base: float,
                 fallback_orig: Optional[int] = None):
    """(inv_freq [dim//2], attention_factor) per transformers
    modeling_rope_utils._compute_yarn_parameters — NTK-by-parts blended
    interpolation/extrapolation frequencies, and the magnitude factor the
    cos/sin tables are multiplied by (the DeepSeek mscale/mscale_all_dim
    variant included). ``fallback_orig``: transformers anchors the
    correction range to max_position_embeddings when the checkpoint omits
    original_max_position_embeddings."""
    factor = float(scaling["factor"])
    orig = (scaling.get("original_max_position_embeddings")
            or fallback_orig)
    if not orig:
        raise ValueError(
            "yarn rope_scaling needs original_max_position_embeddings "
            "(or a max_position fallback) to anchor the correction range")
    orig = float(orig)

    att = scaling.get("attention_factor")
    if att is None:
        mscale = scaling.get("mscale")
        mscale_all_dim = scaling.get("mscale_all_dim")
        if mscale and mscale_all_dim:
            att = float(_yarn_get_mscale(factor, float(mscale))
                        / _yarn_get_mscale(factor, float(mscale_all_dim)))
        else:
            att = _yarn_get_mscale(factor)
    beta_fast = float(scaling.get("beta_fast") or 32)
    beta_slow = float(scaling.get("beta_slow") or 1)

    def corr_dim(rot):
        return (dim * math.log(orig / (rot * 2 * math.pi))
                / (2 * math.log(base)))

    low, high = corr_dim(beta_fast), corr_dim(beta_slow)
    if scaling.get("truncate", True):
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001  # prevent singularity
    ramp = jnp.clip(
        (jnp.arange(dim // 2, dtype=jnp.float32) - low) / (high - low), 0, 1)
    extrap = 1.0 - ramp                     # 1 = keep base freq (short wl)
    pos_freqs = base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    inv_freq = ((1.0 / (factor * pos_freqs)) * (1.0 - extrap)
                + (1.0 / pos_freqs) * extrap)
    return inv_freq, float(att)


def _scale_inv_freq(inv_freq, scaling: Optional[dict]):
    """Apply HF-style rope_scaling to the base frequencies.

    "llama3" (transformers modeling_rope_utils._compute_llama3_parameters):
    wavelengths beyond the original context are divided by ``factor``,
    short wavelengths kept, the band between smoothly interpolated.
    "linear": classic position interpolation (all frequencies / factor).
    "yarn" depends on head_dim/theta and a cos/sin magnitude factor, so it
    is computed in _rope_tables (_yarn_params), not here.
    """
    if not scaling:
        return inv_freq
    rope_type = _rope_type(scaling)
    if rope_type in ("default", "none"):
        return inv_freq
    if rope_type not in SUPPORTED_ROPE_SCALING:
        raise NotImplementedError(
            f"rope_scaling type {rope_type!r} is not implemented "
            f"(supported: {', '.join(sorted(SUPPORTED_ROPE_SCALING))})")
    if rope_type == "yarn":
        raise ValueError(
            "yarn frequencies depend on head_dim/theta — build tables "
            "through _rope_tables(scaling=...)")
    factor = float(scaling["factor"])
    if rope_type == "linear":
        return inv_freq / factor
    if rope_type == "llama3":
        low = float(scaling["low_freq_factor"])
        high = float(scaling["high_freq_factor"])
        orig = float(scaling["original_max_position_embeddings"])
        wavelen = 2.0 * math.pi / inv_freq
        low_wavelen = orig / low
        high_wavelen = orig / high
        smooth = (orig / wavelen - low) / (high - low)
        interp = (1.0 - smooth) / factor + smooth
        scaled = jnp.where(wavelen > low_wavelen, inv_freq / factor, inv_freq)
        in_band = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
        return jnp.where(in_band, interp * inv_freq, scaled)
    raise AssertionError(rope_type)  # unreachable: gated above


def _rope_tables(seq_len, head_dim, theta, dtype=jnp.float32, scaling=None,
                 max_position=None):
    att = 1.0
    if _rope_type(scaling) == "yarn":
        inv_freq, att = _yarn_params(scaling, head_dim, theta,
                                     fallback_orig=max_position)
    elif _rope_type(scaling) == "longrope":
        inv_freq, att = _longrope_params(scaling, head_dim, theta, seq_len,
                                         max_position=max_position)
    else:
        inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
        inv_freq = _scale_inv_freq(inv_freq, scaling)
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [S, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [S, D]
    if att != 1.0:
        # yarn magnitude: cos/sin scaled by the attention factor (HF
        # convention — q·k through the tables picks up att²)
        return jnp.cos(emb) * att, jnp.sin(emb) * att
    return jnp.cos(emb), jnp.sin(emb)


class LlamaRMSNorm(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.hidden_size = config.hidden_size
        self.variance_epsilon = config.rms_norm_eps
        # Gemma parameterizes the norm weight as (1 + w) with w zeros-init
        # (identity at init either way); effective_weight() is what every
        # kernel call must consume
        self.offset = (1.0 if getattr(config, "rms_norm_offset", False)
                       else 0.0)
        self.weight = self.create_parameter(
            [config.hidden_size],
            default_initializer=Constant(0.0 if self.offset else 1.0),
            dtype=config.dtype)

    def effective_weight(self):
        return self.weight + self.offset if self.offset else self.weight

    def forward(self, x):
        from ..ops.pallas import fused_norm

        eps = self.variance_epsilon
        return apply("rms_norm", lambda a, w: fused_norm.rms_norm(a, w, eps),
                     x, self.effective_weight())


def _mp_enabled():
    hcg = get_hybrid_communicate_group()
    return hcg is not None and hcg.get_model_parallel_world_size() > 1


def _make_linear(in_f, out_f, *, column: bool, config: LlamaConfig, gather_output=False,
                 input_is_parallel=True, has_bias=False):
    from ..framework.dtype import dtype_guard

    with dtype_guard(config.dtype):  # params stored in the config dtype
        if _mp_enabled():
            if column:
                cls = (mpu.ColumnSequenceParallelLinear if config.sequence_parallel
                       else mpu.ColumnParallelLinear)
                return cls(in_f, out_f, has_bias=has_bias, gather_output=gather_output)
            cls = (mpu.RowSequenceParallelLinear if config.sequence_parallel
                   else mpu.RowParallelLinear)
            return cls(in_f, out_f, has_bias=has_bias, input_is_parallel=input_is_parallel)
        return nn.Linear(in_f, out_f, bias_attr=None if has_bias else False)


def _make_embedding(config: LlamaConfig):
    """Token embedding, vocab-parallel under mp, Normal-initialized — the
    ONE construction shared by LlamaModel and the pipeline embed stage."""
    from ..framework.dtype import dtype_guard

    with dtype_guard(config.dtype):
        if _mp_enabled() and config.vocab_size % get_hybrid_communicate_group().get_model_parallel_world_size() == 0:
            emb = mpu.VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        else:
            emb = nn.Embedding(config.vocab_size, config.hidden_size)
    emb.weight._array = (
        Normal(0.0, config.initializer_range)(
            (config.vocab_size, config.hidden_size), jnp.float32)
        .astype(emb.weight.dtype))
    return emb


def _scale_embed(hidden, config):
    """Gemma input scaling: hidden * sqrt(hidden_size), with the scalar
    first rounded to the compute dtype (HF casts the normalizer to the
    hidden dtype before multiplying — bf16 parity depends on it)."""
    if not getattr(config, "scale_embeddings", False):
        return hidden
    dt = jax.dtypes.canonicalize_dtype(config.dtype)
    scale = float(np.asarray(math.sqrt(config.hidden_size)).astype(dt))
    return hidden * scale


def _make_lm_head(config: LlamaConfig):
    """Column-parallel lm head, Normal-initialized — shared by
    LlamaForCausalLM and the pipeline head stage."""
    head = _make_linear(config.hidden_size, config.vocab_size,
                        column=True, config=config, gather_output=True)
    head.weight._array = (
        Normal(0.0, config.initializer_range)(
            (config.hidden_size, config.vocab_size), jnp.float32)
        .astype(head.weight.dtype))
    return head


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = head_dim_of(config)
        # per-INSTANCE sliding window: defaults to the config's uniform
        # value; alternating-window families (Gemma2) set it per layer
        self.window = config.sliding_window
        # Gemma2 softmax-scale override, folded into q once after
        # projection: every downstream path divides by sqrt(head_dim), so
        # multiplying q by sqrt(head_dim)/sqrt(query_pre_attn_scalar)
        # yields the target scale exactly (RoPE is linear and commutes)
        qpas = getattr(config, "query_pre_attn_scalar", None)
        self.q_premul = (math.sqrt(self.head_dim / qpas) if qpas else None)
        bias = config.attention_bias
        self.qk_norm_mode = ("per_head" if config.qk_norm is True
                             else (config.qk_norm or None))
        if self.qk_norm_mode == "per_head":
            # Qwen3: per-head RMSNorm on q/k after projection, before RoPE
            self.q_norm = _width_norm(config, self.head_dim)
            self.k_norm = _width_norm(config, self.head_dim)
        elif self.qk_norm_mode == "full":
            # OLMo2: ONE norm over the whole projected q (and k) width
            self.q_norm = _width_norm(config,
                                      self.num_heads * self.head_dim)
            self.k_norm = _width_norm(config,
                                      self.num_kv_heads * self.head_dim)
        else:
            self.q_norm = self.k_norm = None
        self.q_proj = _make_linear(self.hidden_size, self.num_heads * self.head_dim,
                                   column=True, config=config, has_bias=bias)
        self.k_proj = _make_linear(self.hidden_size, self.num_kv_heads * self.head_dim,
                                   column=True, config=config, has_bias=bias)
        self.v_proj = _make_linear(self.hidden_size, self.num_kv_heads * self.head_dim,
                                   column=True, config=config, has_bias=bias)
        self.o_proj = _make_linear(self.num_heads * self.head_dim, self.hidden_size,
                                   column=False, config=config)

    def cached_attn_core(self, q, k, v, cos, sin, kv_cache,
                         rope_applied=False):
        """Attention against the static-shape decode cache (serving
        path): jit-stable shapes at every step. Two layouts, both with
        in-place buffer updates: dense [B,Smax,hk,d], or paged (block
        tables) matching block_multi_head_attention_kernel.cu.
        ``allowed`` is an optional [B,T] column-validity mask (padded
        prompts). ``rope_applied``: q/k arrive pre-rotated (the fused
        decode-tail kernel). Returns (out [b, s, H*D] BEFORE o_proj,
        new_cache) — split from o_proj so the fused epilogue can take
        the projection into its own kernel."""
        from ..generation import cached_attention, paged_cached_attention

        b, s = q.shape[0], q.shape[1]
        h, d = self.num_heads, self.head_dim
        cfg = self.config
        softcap = getattr(cfg, "attn_logit_softcapping", None)
        if "k_pages" in kv_cache:
            out, kp, vp = apply(
                "llama_attention_paged", paged_cached_attention,
                q, k, v, cos, sin, kv_cache["k_pages"],
                kv_cache["v_pages"], kv_cache["page_indices"],
                kv_cache["lengths"], kv_cache.get("page_size"),
                window=self.window, softcap=softcap,
                rope_applied=rope_applied)
            new = dict(kv_cache)
            new.update(k_pages=kp, v_pages=vp,
                       lengths=kv_cache["lengths"] + s)
            return out.reshape([b, s, h * d]), new
        out, k_buf, v_buf = apply(
            "llama_attention_cached", cached_attention, q, k, v, cos, sin,
            kv_cache["k"], kv_cache["v"], kv_cache["pos"],
            kv_cache.get("allowed"), kv_cache.get("row_pos"),
            use_flash=(cfg.use_flash_attention and softcap is None),
            prefill=bool(kv_cache.get("prefill", False)),
            window=self.window, softcap=softcap,
            rope_applied=rope_applied)
        new = {"k": k_buf, "v": v_buf, "pos": kv_cache["pos"] + s}
        if "allowed" in kv_cache:
            new["allowed"] = kv_cache["allowed"]
        if "row_pos" in kv_cache:
            # per-row RoPE positions ADVANCE with each decoded token —
            # frozen positions would rotate every generated token of a
            # padded row at the same angle (review r4: ragged decode
            # diverged from the solo run from the 5th token on)
            new["row_pos"] = kv_cache["row_pos"] + s
        return out.reshape([b, s, h * d]), new

    def decode_fused_qkv(self, hidden_states, norm_weight, eps, cos, sin,
                         kv_cache):
        """Fused ``rms_norm → q/k/v → rope`` through the decode-tail
        megakernel (ops/pallas/decode_tail) — the caller has verified
        the gate (fused_decode_supported). S=1 is the classic decode
        step; an S>1 speculative-verify chunk flattens to B*S independent
        rows (the kernels are row-parallel, and each row's rope position
        is gathered per row). Returns (q, k, v) shaped like the discrete
        projections, q/k already rotated at each row's cache position."""
        from ..ops.pallas import decode_tail

        b, s = hidden_states.shape[0], hidden_states.shape[1]
        h, hk, d = self.num_heads, self.num_kv_heads, self.head_dim
        cos_r, sin_r = _rope_rows_for_cache(cos, sin, kv_cache, b, s)
        q2, k2, v2 = apply(
            "fused_decode_qkv",
            lambda x2, wn, wq, wk, wv, c, s_: decode_tail.fused_qkv_rope(
                x2, wn, wq, wk, wv, c, s_, eps, h, hk, d),
            hidden_states.reshape([b * s, self.hidden_size]), norm_weight,
            self.q_proj.weight, self.k_proj.weight, self.v_proj.weight,
            cos_r, sin_r)
        return (q2.reshape([b, s, h, d]), k2.reshape([b, s, hk, d]),
                v2.reshape([b, s, hk, d]))

    def forward(self, hidden_states, cos, sin, attention_mask=None, kv_cache=None, position_offset=0):
        b, s = hidden_states.shape[0], hidden_states.shape[1]
        h, hk, d = self.num_heads, self.num_kv_heads, self.head_dim
        q_flat = self.q_proj(hidden_states)
        k_flat = self.k_proj(hidden_states)
        if self.qk_norm_mode == "full":   # OLMo2: norm BEFORE head split
            q_flat = self.q_norm(q_flat)
            k_flat = self.k_norm(k_flat)
        q = q_flat.reshape([b, s, h, d])
        k = k_flat.reshape([b, s, hk, d])
        v = self.v_proj(hidden_states).reshape([b, s, hk, d])
        if self.qk_norm_mode == "per_head":
            q = self.q_norm(q)
            k = self.k_norm(k)
        if self.q_premul is not None:
            q = q * self.q_premul

        cfg = self.config
        softcap = getattr(cfg, "attn_logit_softcapping", None)

        if isinstance(kv_cache, dict):
            out_flat, new = self.cached_attn_core(q, k, v, cos, sin,
                                                  kv_cache)
            return self.o_proj(out_flat), new

        def attn_fn(q, k, v, cos, sin, *cache):
            from ..ops.pallas import fused_norm, flash_attention as pf
            from ..nn.functional.attention import _sdpa_ref

            q = fused_norm.apply_rope(q, cos, sin)
            k = fused_norm.apply_rope(k, cos, sin)
            if cache:
                k = jnp.concatenate([cache[0], k], axis=1)
                v = jnp.concatenate([cache[1], v], axis=1)
            win = self.window
            if win is not None and win <= 0:
                raise ValueError("sliding_window must be positive")
            hcg = get_hybrid_communicate_group()
            cp_active = (not cache and hcg is not None
                         and hcg.get_sep_parallel_world_size() > 1
                         and cfg.sep_mode in ("ring", "ulysses"))
            if softcap is not None and cp_active:
                raise NotImplementedError(
                    "attn_logit_softcapping under context parallelism is "
                    "not supported (the ring/Ulysses kernels compute "
                    "uncapped scores)")
            if cp_active:
                # context parallelism: sequence stays sharded over sep; k/v
                # blocks ride the ring (or heads ride an all-to-all) instead
                # of GSPMD all-gathering the whole sequence per device.
                # k/v enter UNexpanded: the CP kernels handle GQA internally,
                # so the ring moves num_kv_heads worth of bytes, not num_heads.
                import functools

                from ..distributed.collective import shard_map
                from jax.sharding import PartitionSpec as P

                from ..distributed.context_parallel import (
                    cp_mesh_axes, ring_attention, ulysses_attention)

                mesh, batch_ax, head_ax = cp_mesh_axes(hcg)
                spec = P(batch_ax, "sep", head_ax, None)
                inner = (ring_attention if cfg.sep_mode == "ring"
                         else ulysses_attention)
                cp = shard_map(
                    functools.partial(inner, axis_name="sep", causal=True,
                                      window=win),
                    mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                    # splash-per-hop ring runs pallas_call inside the
                    # shard_map; pallas outputs carry no vma, so the vma
                    # checker must be off (the jax-documented pairing)
                    check_vma=False)
                out = cp(q, k, v)
            elif (cfg.use_flash_attention and softcap is None
                  and pf.supported(q, k, v)):
                # GQA-native splash kernel: KV stays at num_kv_heads width
                # through HBM (no _expand_gqa on the hot path)
                out = pf.flash_attention_bshd(q, k, v, causal=True, window=win)
            else:
                from ..distributed.context_parallel import _expand_gqa

                ke, ve = _expand_gqa(k, v, h)
                band = None
                if win is not None:
                    sq, sk = q.shape[1], k.shape[1]
                    off = sk - sq
                    rows = jnp.arange(sq)[:, None] + off
                    cols = jnp.arange(sk)[None, :]
                    band = ((cols <= rows) & (cols > rows - win))[None, None]
                out = _sdpa_ref(q, ke, ve, causal=band is None, mask=band,
                                softcap=softcap)
            return out.reshape(b, out.shape[1], h * d), k, v

        cache_args = [kv_cache[0], kv_cache[1]] if kv_cache is not None else []
        out, k_new, v_new = apply("llama_attention", attn_fn, q, k, v, cos, sin, *cache_args)
        result = self.o_proj(out)
        if kv_cache is not None:
            return result, (k_new, v_new)
        return result


class LlamaMLP(Layer):
    """Gated MLP: SwiGLU (silu gate — Llama) or GeGLU (tanh-gelu gate —
    Gemma), selected by ``config.hidden_act``."""

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.hidden_act = getattr(config, "hidden_act", "silu")
        self.gate_proj = _make_linear(config.hidden_size, config.intermediate_size,
                                      column=True, config=config)
        self.up_proj = _make_linear(config.hidden_size, config.intermediate_size,
                                    column=True, config=config)
        self.down_proj = _make_linear(config.intermediate_size, config.hidden_size,
                                      column=False, config=config)

    def forward(self, x):
        gate = self.gate_proj(x)
        up = self.up_proj(x)
        if self.hidden_act == "gelu_pytorch_tanh":
            act = apply("geglu",
                        lambda g, u: jax.nn.gelu(g, approximate=True) * u,
                        gate, up)
        else:
            act = apply("swiglu", lambda g, u: jax.nn.silu(g) * u, gate, up)
        return self.down_proj(act)


def _rope_rows_for_cache(cos, sin, kv_cache, b, s=1):
    """cos/sin rows at each row's CURRENT decode position(s), [B*S, D]
    f32 — the fused decode-tail kernel ropes in-register, so the (tiny)
    table gather happens here: paged caches decode at per-row
    ``lengths`` (token j of a speculative-verify chunk sits at
    lengths[b]+j), ragged dense at ``row_pos``, plain dense batches
    share the scalar ``pos``. ``s > 1`` is paged-only (the gate keeps
    dense chunks on the discrete path)."""
    cos_a, sin_a = unwrap(cos), unwrap(sin)
    if "k_pages" in kv_cache:
        base = jnp.asarray(unwrap(kv_cache["lengths"]), jnp.int32)
        if s == 1:
            idx = base
        else:
            idx = (base[:, None]
                   + jnp.arange(s, dtype=jnp.int32)[None, :]).reshape(-1)
    elif "row_pos" in kv_cache:
        idx = jnp.asarray(unwrap(kv_cache["row_pos"]), jnp.int32)
    else:
        pos = jnp.asarray(unwrap(kv_cache["pos"]), jnp.int32)
        c = jax.lax.dynamic_slice_in_dim(cos_a, pos, 1, 0)
        s_ = jax.lax.dynamic_slice_in_dim(sin_a, pos, 1, 0)
        return (jnp.broadcast_to(c, (b, c.shape[-1])),
                jnp.broadcast_to(s_, (b, s_.shape[-1])))
    return cos_a[idx], sin_a[idx]


def fused_decode_structural(layer, dtype) -> bool:
    """The WEIGHT-STRUCTURE half of the fused decode-tail gate: does
    this decoder layer look like what the megakernels assume — llama
    attention with no qk-norm, no q pre-multiplier, no projection
    bias, no tensor parallelism (plain ``nn.Linear``), dtype-uniform
    weights and RMSNorm scales. Shape/cache/VMEM feasibility is the
    dynamic half (``fused_decode_supported``); this half is also what
    the ``fused-coverage`` pdlint rule sweeps the model zoo with — a
    family regressing off the fused path fails that gate, not a perf
    bisect three weeks later."""
    attn = getattr(layer, "self_attn", None)
    if not isinstance(attn, LlamaAttention):
        return False
    if attn.qk_norm_mode is not None or attn.q_premul is not None:
        return False
    lins = (attn.q_proj, attn.k_proj, attn.v_proj, attn.o_proj)
    if any(type(l) is not nn.Linear or l.bias is not None for l in lins):
        return False
    if any(unwrap(l.weight).dtype != dtype for l in lins):
        return False
    norms = (getattr(layer, "input_layernorm", None),
             getattr(layer, "post_attention_layernorm", None))
    if any(not isinstance(n, LlamaRMSNorm)
           or unwrap(n.weight).dtype != dtype for n in norms):
        return False
    return True


def fused_decode_supported(layer, hidden_states, kv_cache, cos) -> bool:
    """Trace-time gate for the fused decode tail
    (FLAGS_use_fused_decode_tail): the structural predicate above on a
    dict decode cache, plus decode_tail's own VMEM-feasibility gate.
    S=1 is the classic decode step; an S>1 PAGED chunk (the engine's
    speculative verify) also qualifies — it flattens to B*S independent
    rows with per-row rope positions. Anything else keeps the discrete
    reference kernels (exact parity by construction)."""
    from ..ops.pallas import decode_tail

    if not decode_tail.enabled() or not isinstance(kv_cache, dict):
        return False
    if hidden_states.shape[1] != 1 and "k_pages" not in kv_cache:
        return False
    x = unwrap(hidden_states)
    if not fused_decode_structural(layer, x.dtype):
        return False
    attn = layer.self_attn
    return decode_tail.supported(
        x.shape[0] * x.shape[1], attn.hidden_size, attn.num_heads,
        attn.num_kv_heads, attn.head_dim, unwrap(cos).shape[-1],
        jnp.dtype(x.dtype).itemsize)


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)

    def _forward_fused_decode(self, hidden_states, cos, sin, kv_cache):
        """The serving decode tail as two megakernel dispatches around
        the attention kernel (ops/pallas/decode_tail): norm→qkv→rope
        fused, then o_proj→residual-add→norm fused — per-token
        activations stay in VMEM instead of 4-6 HBM round trips per
        layer. An S>1 speculative-verify chunk rides the SAME kernels as
        B*S flattened rows. Token-identical to the discrete path (tier-1
        parity test)."""
        from ..ops.pallas import decode_tail

        attn = self.self_attn
        b, s = hidden_states.shape[0], hidden_states.shape[1]
        decode_tail.announce(
            "paged" if "k_pages" in kv_cache else "dense", b * s,
            attn.hidden_size, attn.num_heads, attn.num_kv_heads,
            attn.head_dim)
        q, k, v = attn.decode_fused_qkv(
            hidden_states, self.input_layernorm.effective_weight(),
            self.input_layernorm.variance_epsilon, cos, sin, kv_cache)
        out_flat, new_cache = attn.cached_attn_core(
            q, k, v, cos, sin, kv_cache, rope_applied=True)
        eps = self.post_attention_layernorm.variance_epsilon
        normed, residual = apply(
            "fused_decode_epilogue",
            lambda a, wo, r, w: decode_tail.fused_epilogue(a, wo, r, w,
                                                           eps),
            out_flat.reshape([b * s, attn.num_heads * attn.head_dim]),
            attn.o_proj.weight,
            hidden_states.reshape([b * s, attn.hidden_size]),
            self.post_attention_layernorm.effective_weight())
        hidden_states = residual.reshape([b, s, attn.hidden_size]) + \
            self.mlp(normed.reshape([b, s, attn.hidden_size]))
        return hidden_states, new_cache

    def forward(self, hidden_states, cos, sin, attention_mask=None, kv_cache=None):
        from ..ops.pallas import fused_norm

        if kv_cache is not None and fused_decode_supported(
                self, hidden_states, kv_cache, cos):
            return self._forward_fused_decode(hidden_states, cos, sin,
                                              kv_cache)
        residual = hidden_states
        hidden_states = self.input_layernorm(hidden_states)
        if kv_cache is not None:
            hidden_states, kv_cache = self.self_attn(hidden_states, cos, sin,
                                                     attention_mask, kv_cache)
        else:
            hidden_states = self.self_attn(hidden_states, cos, sin, attention_mask)
        # fused residual-add + RMSNorm (Pallas): h = residual + attn_out is
        # written once and normed in the same HBM pass; h doubles as the next
        # residual (the block's hottest bandwidth pattern — VERDICT r2 item 1)
        eps = self.post_attention_layernorm.variance_epsilon
        hidden_states, residual = apply(
            "add_rms_norm",
            lambda a, r, w: fused_norm.add_rms_norm(a, r, w, eps),
            hidden_states, residual,
            self.post_attention_layernorm.effective_weight())
        hidden_states = residual + self.mlp(hidden_states)
        if kv_cache is not None:
            return hidden_states, kv_cache
        return hidden_states


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.embed_tokens = _make_embedding(config)
        layers = [self._make_decoder_layer(config, i)
                  for i in range(config.num_hidden_layers)]
        if config.recompute:
            from ..distributed.recompute_layer import RecomputeLayer

            layers = [RecomputeLayer(l) for l in layers]
        self.layers = nn.LayerList(layers)
        self.norm = LlamaRMSNorm(config)
        self._rope_cache = {}

    @staticmethod
    def _make_decoder_layer(config, layer_idx):
        """Per-layer construction hook — families with per-layer structure
        (Gemma2's sandwich norms) override this. The per-layer window
        schedule (``layer_types``) is applied here for every family."""
        layer = LlamaDecoderLayer(config)
        layer.self_attn.window = layer_window(config, layer_idx)
        return layer

    def _rope_dim(self):
        """Rotary table width; MLA trunks override (RoPE rides only the
        decoupled qk_rope_head_dim slice)."""
        return rope_dim_of(self.config)

    def _rope(self, seq_len):
        if seq_len in self._rope_cache:
            return self._rope_cache[seq_len]
        cos, sin = _rope_tables(seq_len, self._rope_dim(),
                                self.config.rope_theta,
                                scaling=self.config.rope_scaling,
                                max_position=self.config.max_position_embeddings)
        pair = (wrap(cos), wrap(sin))
        # memoize only outside traces (a traced constant must not escape)
        from ..jit import is_tracing

        if not is_tracing():
            self._rope_cache[seq_len] = pair
        return pair

    def forward(self, input_ids, attention_mask=None, return_prenorm=False,
                inputs_embeds=None):
        s = (input_ids if inputs_embeds is None else inputs_embeds).shape[1]
        cos, sin = self._rope(s)
        if inputs_embeds is None:
            hidden = self.embed_tokens(input_ids)
            hidden = _scale_embed(hidden.astype(self.config.dtype),
                                  self.config)
        else:
            # multimodal path (LLaVA): embeddings already merged with image
            # features — scaling (if any) was applied at merge time
            hidden = inputs_embeds
        for layer in self.layers:
            hidden = layer(hidden, cos, sin, attention_mask)
        if return_prenorm:
            # (normed, pre-norm) — the MTP chain consumes the pre-norm
            # last-layer representation (arXiv:2412.19437 §2.2)
            return self.norm(hidden), hidden
        return self.norm(hidden)

    def forward_cached(self, input_ids, kv_caches, rope_len,
                       return_prenorm=False, inputs_embeds=None):
        """Decode-path forward over static KV caches (one dict per layer,
        see generation.cached_attention). Returns (hidden, new_caches) —
        or (normed, prenorm, new_caches) with ``return_prenorm`` (the MTP
        speculative draft consumes the pre-norm stream).
        ``inputs_embeds``: pre-merged embeddings (LLaVA prefill) — skips
        the token embedding."""
        cos, sin = self._rope(rope_len)
        if inputs_embeds is None:
            hidden = self.embed_tokens(input_ids)
            hidden = _scale_embed(hidden.astype(self.config.dtype),
                                  self.config)
        else:
            hidden = inputs_embeds
        new_caches = []
        for layer, cache in zip(self.layers, kv_caches):
            inner = getattr(layer, "inner", layer)  # unwrap RecomputeLayer
            hidden, c = inner(hidden, cos, sin, kv_cache=cache)
            new_caches.append(c)
        if return_prenorm:
            return self.norm(hidden), hidden, new_caches
        return self.norm(hidden), new_caches


class LlamaForCausalLM(Layer):
    model_cls = LlamaModel  # trunk hook (Gemma2 swaps in sandwich norms)

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.llama = type(self).model_cls(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = _make_lm_head(config)

    def lm_head_logits(self, hidden):
        if self.lm_head is None:
            logits = tied_lm_head_logits(hidden,
                                         self.llama.embed_tokens.weight)
        else:
            logits = self.lm_head(hidden)
        cap = getattr(self.config, "final_logit_softcapping", None)
        if cap:
            # Gemma2 tanh soft cap — applied HERE so every consumer
            # (training loss, generate, beam, speculative, serving) and
            # every family on the trunk (MoE included) gets it
            logits = apply("final_logit_softcap",
                           lambda x: cap * jnp.tanh(x / cap), logits)
        return logits

    def generate(self, input_ids, max_new_tokens=20, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 use_cache=True, attention_mask=None, paged=False,
                 page_size=16, prefill_chunk_size=None,
                 repetition_penalty=1.0, min_new_tokens=0,
                 num_beams=1, length_penalty=1.0, early_stopping=False,
                 no_repeat_ngram_size=0):
        """Batched autoregressive decode (see paddle_tpu.generation)."""
        from ..generation import generate as _generate

        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         do_sample=do_sample, temperature=temperature,
                         top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
                         use_cache=use_cache, attention_mask=attention_mask,
                         paged=paged, page_size=page_size,
                         prefill_chunk_size=prefill_chunk_size,
                         repetition_penalty=repetition_penalty,
                         min_new_tokens=min_new_tokens, num_beams=num_beams,
                         length_penalty=length_penalty,
                         early_stopping=early_stopping,
                         no_repeat_ngram_size=no_repeat_ngram_size)

    def forward(self, input_ids, labels=None, attention_mask=None):
        hidden = self.llama(input_ids, attention_mask)
        if labels is not None and self.config.fuse_linear_cross_entropy:
            # mp note: parallel weights in this build are GLOBAL jax.Arrays
            # (vocab sharding lives in the array's NamedSharding, GSPMD
            # partitions the contraction), so the fused op computes the
            # full-vocab logsumexp under mp too — mp2 training-trajectory
            # parity is tested for both the ColumnParallel head and the
            # tied VocabParallel embedding (tests/test_fused_loss.py).
            # sequence_parallel heads are NOT verified with the chunked
            # scan and fall through to the (correct) logits path, as do
            # swapped heads (WeightOnlyLinear, LoRALinear, ...) whose
            # logits come from their own forward
            head_ok = (not self.config.sequence_parallel
                       and (self.lm_head is None
                            or isinstance(self.lm_head,
                                          (nn.Linear,
                                           mpu.ColumnParallelLinear))))
            if head_ok:
                from ..ops.fused_loss import fused_linear_cross_entropy

                if self.lm_head is None:  # tied: embedding weight [vocab, hidden]
                    w, layout = self.llama.embed_tokens.weight, "vh"
                else:
                    w, layout = self.lm_head.weight, "hv"
                loss = apply(
                    "fused_linear_cross_entropy",
                    lambda h, ww, lb: fused_linear_cross_entropy(h, ww, lb,
                                                                 layout),
                    hidden, w, labels)
                return loss, None
        logits = self.lm_head_logits(hidden)
        if labels is None:
            return logits
        return causal_lm_loss(logits, labels), logits

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


def tied_lm_head_logits(hidden, embed_weight):
    """Project with the shared embedding weight [vocab, hidden] — the ONE
    tied-head contraction used by every tied causal LM (Llama family,
    GPT-2, the pipeline head stage)."""
    return apply("tied_lm_head", lambda h, w: h @ w.T, hidden, embed_weight)


def causal_lm_loss(logits, labels):
    """Token-mean causal-LM cross entropy in f32; labels < 0 are ignored
    (the loss the reference's PaddleNLP criterion computes)."""
    def loss_fn(lg, lb):
        lg32 = lg.astype(jnp.float32)
        logp = jax.nn.log_softmax(lg32, axis=-1)
        idx = lb.astype(jnp.int32)
        mask = idx >= 0
        safe = jnp.where(mask, idx, 0)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        nll = jnp.where(mask, nll, 0.0)
        return jnp.sum(nll) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)

    return apply("causal_lm_loss", loss_fn, logits, labels)


# ---------------------------------------------------------------------------
# pipeline-parallel Llama (the PaddleNLP LlamaForCausalLMPipe pattern)
# ---------------------------------------------------------------------------

from ..distributed.pipeline import LayerDesc, PipelineLayer  # noqa: E402


class LlamaEmbeddingPipe(Layer):
    """First pipeline stage: token embedding (vocab-parallel under mp).
    With tie_word_embeddings it is ALSO the head stage's shared layer
    (SharedLayerDesc) — `_tied_head_forward` projects with the same weight."""

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.embed_tokens = _make_embedding(config)

    def forward(self, input_ids):
        return _scale_embed(self.embed_tokens(input_ids)
                            .astype(self.config.dtype), self.config)


def _tied_head_forward(layer: "LlamaEmbeddingPipe", hidden):
    """Head forward over the SHARED embedding weight (tied lm head)."""
    return tied_lm_head_logits(hidden, layer.embed_tokens.weight)


class LlamaDecoderLayerPipe(Layer):
    """One decoder layer as a pipeline item: computes its own RoPE tables
    from the activation's seq length (constant-folded by XLA inside the
    stage jit) so only [B, S, H] crosses stage boundaries.

    Subclass hooks: ``decoder_cls`` (the wrapped layer class, given
    ``(config, *extra_args)``) and ``_rope_dim`` (table width — MLA
    families rope only their decoupled slice)."""

    decoder_cls = LlamaDecoderLayer

    def __init__(self, config: LlamaConfig, *layer_args):
        super().__init__(dtype=config.dtype)
        self.config = config
        layer = type(self).decoder_cls(config, *layer_args)
        if config.recompute:
            from ..distributed.recompute_layer import RecomputeLayer

            layer = RecomputeLayer(layer)
        self.layer = layer

    def _rope_dim(self):
        return rope_dim_of(self.config)

    def forward(self, hidden):
        cfg = self.config
        cos, sin = _rope_tables(hidden.shape[1], self._rope_dim(),
                                cfg.rope_theta, scaling=cfg.rope_scaling,
                                max_position=cfg.max_position_embeddings)
        return self.layer(hidden, wrap(cos), wrap(sin))


class LlamaNormHeadPipe(Layer):
    """Last pipeline stage: final RMSNorm + (untied) lm head."""

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.norm = LlamaRMSNorm(config)
        self.lm_head = _make_lm_head(config)

    def forward(self, hidden):
        return self.lm_head(self.norm(hidden))


class LlamaNormPipe(Layer):
    """Final RMSNorm alone (tied-head layout: the head is the shared
    embedding layer that follows this item)."""

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.norm = LlamaRMSNorm(config)

    def forward(self, hidden):
        return self.norm(hidden)


class LlamaForCausalLMPipe(PipelineLayer):
    """Stage-partitioned Llama causal LM (PaddleNLP LlamaForCausalLMPipe
    pattern over this build's PipelineLayer/PipelineParallel runtime).

    Train with ``fleet.distributed_model(model)`` under an hcg with
    pp_degree > 1 — each stage's mp/sharding placements ride its submesh
    (pipeline.py hybrid mode) — then ``pp.train_batch([ids, labels], opt)``
    with ``labels`` already shifted (same contract as LlamaForCausalLM).

    Subclass hooks (the DeepSeek pipe reuses this assembly verbatim):
    ``decoder_pipe_cls``, ``shared_embed_key``, ``_decoder_args`` (extra
    per-layer ctor args) and ``_check_config`` (family guards).
    """

    decoder_pipe_cls = LlamaDecoderLayerPipe
    shared_embed_key = "llama_embed"

    def _decoder_args(self, config, layer_idx):
        return (config,)

    def _check_config(self, config):
        if config.fuse_linear_cross_entropy:
            # the pipeline head stage emits full logits into the pipeline
            # loss; honoring the flag would need a fused head+loss stage —
            # raise rather than silently skip the memory saving
            raise NotImplementedError(
                "fuse_linear_cross_entropy is not supported by the pipeline "
                f"head stage; unset the flag for {type(self).__name__}")
        if getattr(config, "layer_types", None):
            # pipe decoder items are index-free LayerDescs; honoring the
            # schedule needs per-item window plumbing — raise rather than
            # silently attend full/sliding on the wrong layers
            raise NotImplementedError(
                "the per-layer window schedule (layer_types) is not "
                f"supported under {type(self).__name__}")
        if getattr(config, "final_logit_softcapping", None):
            # the pipe head stages project with the raw weight (no
            # lm_head_logits hook)
            raise NotImplementedError(
                "final_logit_softcapping is not supported by the pipeline "
                f"head stage of {type(self).__name__}")

    def __init__(self, config: LlamaConfig, num_stages=None,
                 seg_method=None, **pipe_kwargs):
        cls = type(self)
        if seg_method is None:
            seg_method = f"layer:{cls.decoder_pipe_cls.__name__}"
        self._check_config(config)
        if num_stages is None:
            hcg = get_hybrid_communicate_group()
            num_stages = (hcg.get_pipe_parallel_world_size()
                          if hcg is not None else 1)
        decoders = [LayerDesc(cls.decoder_pipe_cls,
                              *self._decoder_args(config, i))
                    for i in range(config.num_hidden_layers)]
        if config.tie_word_embeddings:
            from ..distributed.pipeline import SharedLayerDesc

            descs = ([SharedLayerDesc(cls.shared_embed_key,
                                      LlamaEmbeddingPipe,
                                      None, "weight", config)]
                     + decoders
                     + [LayerDesc(LlamaNormPipe, config),
                        SharedLayerDesc(cls.shared_embed_key,
                                        LlamaEmbeddingPipe,
                                        _tied_head_forward, "weight",
                                        config)])
        else:
            descs = ([LayerDesc(LlamaEmbeddingPipe, config)]
                     + decoders
                     + [LayerDesc(LlamaNormHeadPipe, config)])
        super().__init__(descs, num_stages=num_stages,
                         loss_fn=causal_lm_loss, seg_method=seg_method,
                         **pipe_kwargs)
        self.config = config


# ---------------------------------------------------------------------------
# HuggingFace checkpoint interop
# ---------------------------------------------------------------------------

def _hf_to_np(v):
    try:
        import torch

        if isinstance(v, torch.Tensor):
            return v.detach().to(torch.float32).cpu().numpy()
    except ImportError:  # pragma: no cover
        pass
    return np.asarray(v)


def hf_config_to_llama(hf_config, **overrides) -> LlamaConfig:
    """Map a transformers LlamaConfig (object or dict) onto LlamaConfig."""
    get = _hf_get(hf_config)
    # a Gemma checkpoint has EXACTLY Llama's key layout, so loading it
    # through the plain-llama mapper would succeed and silently compute
    # garbage ((1+w)-delta norms read as full weights, unscaled embeddings,
    # silu instead of geglu) — refuse unless the Gemma knobs arrive via
    # overrides (gemma_from_hf sets them)
    if (str(get("model_type", "")).startswith("gemma")
            and "rms_norm_offset" not in overrides):
        raise NotImplementedError(
            "this checkpoint is a Gemma-family model — convert it with "
            "gemma_from_hf (llama_from_hf would misread its (1+w) norm "
            "deltas and unscaled embeddings)")
    # type + parameter gate at CONVERT time (yarn math errors included)
    scaling = mapped_rope_scaling(get)
    # HF Llama's attention_bias puts bias on q/k/v AND o; this build only
    # represents q/k/v bias (the Qwen2 layout) — map the Qwen2-style flag,
    # refuse a checkpoint that would carry an o_proj bias
    window = None
    if get("use_sliding_window", get("sliding_window") is not None
           and get("model_type") == "mistral"):
        window = get("sliding_window")
        # HF Qwen2 applies the window only to layers >= max_window_layers;
        # this build's window is uniform — a mixed-layer checkpoint loaded
        # uniformly would silently compute different logits than its
        # reference, so refuse it (0 = every layer windowed is exact)
        mwl = get("max_window_layers", 0) or 0
        if 0 < mwl < get("num_hidden_layers"):
            raise NotImplementedError(
                f"hf_config_to_llama: per-layer sliding window "
                f"(max_window_layers={mwl}) is not supported — this build "
                "applies sliding_window uniformly")
        if mwl >= get("num_hidden_layers"):
            window = None  # no layer is windowed in the HF semantics
    kw = dict(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads",
                                get("num_attention_heads")),
        max_position_embeddings=get("max_position_embeddings"),
        rms_norm_eps=get("rms_norm_eps", 1e-5),
        rope_theta=get("rope_theta", 10000.0),
        rope_scaling=scaling,
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
        attention_bias=bool(get("attention_bias",
                                get("model_type") == "qwen2")),
        head_dim=get("head_dim"),
        partial_rotary_factor=float(get("partial_rotary_factor") or 1.0),
        sliding_window=window,
    )
    kw.update(overrides)
    return LlamaConfig(**kw)


#: the classic per-layer norm pair of the Llama key layout (OLMo2 swaps
#: in its post-only pair, Gemma2 appends its sandwich norms)
_DEFAULT_LAYER_NORMS = ("input_layernorm", "post_attention_layernorm")


def _hf_llama_plan(model, extra_layer_norms=(), layer_norms=None):
    """{our param name: (hf key, transpose)} for the Llama key layout —
    the ONE mapping shared by the loader and the reverse exporter. The
    (untied) lm head maps to "lm_head.weight"; loaders may redirect its
    source for tied-in-HF checkpoints. ``layer_norms=None`` resolves to
    the classic pair here (the single source of that default)."""
    if layer_norms is None:
        layer_norms = _DEFAULT_LAYER_NORMS
    L = model.config.num_hidden_layers
    plan = {"llama.embed_tokens.weight": ("model.embed_tokens.weight", False),
            "llama.norm.weight": ("model.norm.weight", False)}
    for i in range(L):
        hf, ours = f"model.layers.{i}", f"llama.layers.{i}"
        for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
            plan[f"{ours}.self_attn.{proj}.weight"] = (
                f"{hf}.self_attn.{proj}.weight", True)
        if model.config.qk_norm:
            for norm in ("q_norm", "k_norm"):  # per-head RMSNorm (Qwen3)
                plan[f"{ours}.self_attn.{norm}.weight"] = (
                    f"{hf}.self_attn.{norm}.weight", False)
        if model.config.attention_bias:
            for proj in ("q_proj", "k_proj", "v_proj"):  # o_proj stays bias-free
                plan[f"{ours}.self_attn.{proj}.bias"] = (
                    f"{hf}.self_attn.{proj}.bias", False)
        for proj in ("gate_proj", "up_proj", "down_proj"):
            plan[f"{ours}.mlp.{proj}.weight"] = (f"{hf}.mlp.{proj}.weight", True)
        for norm in tuple(layer_norms) + tuple(extra_layer_norms):
            # default: the classic input/post_attention pair; Gemma2 adds
            # its sandwich norms; OLMo2 swaps in its post-only pair
            plan[f"{ours}.{norm}.weight"] = (f"{hf}.{norm}.weight", False)
    if model.lm_head is not None:
        plan["lm_head.weight"] = ("lm_head.weight", True)
    return plan


def export_hf_llama(model: "LlamaForCausalLM", extra_layer_norms=(),
                    layer_norms=None, dtype=None):
    """The reverse of load_hf_llama: this model's weights as an
    HF-key-layout numpy state dict (torch [out, in] projection layout),
    ready for ``HFModel.load_state_dict`` via torch.from_numpy — train
    here, deploy anywhere. Tied models omit lm_head.weight (HF re-ties
    from the embedding). Round-trip parity is tested per family.

    Dtype: each tensor keeps the PARAMETER's dtype (a bf16 model exports
    a bf16 checkpoint at half the bytes of the old unconditional float32
    upcast — note bf16 arrays carry the ``ml_dtypes`` numpy dtype, which
    recent torch/safetensors understand; pass ``dtype="float32"`` for
    consumers that don't). ``dtype`` forces a uniform cast when set."""
    plan = _hf_llama_plan(model, extra_layer_norms=extra_layer_norms,
                          layer_norms=layer_norms)
    params = dict(model.named_parameters())
    out = {}
    for name, (hf_key, transpose) in plan.items():
        if name not in params:
            raise KeyError(f"export_hf_llama: model has no param {name!r}")
        v = np.asarray(unwrap(params[name]))
        if dtype is not None:
            v = v.astype(dtype)
        out[hf_key] = v.T if transpose else v
    return out


def load_hf_llama(model: "LlamaForCausalLM", hf_state_dict,
                  extra_layer_norms=(), layer_norms=None,
                  ignore_missing_prefixes=()) -> "LlamaForCausalLM":
    """Load a HuggingFace Llama checkpoint's state dict into ``model``.

    Accepts torch tensors or arrays. torch ``nn.Linear`` stores weights
    [out, in]; this build stores [in, out] (paddle convention), so every
    projection transposes. Config names follow HF conventions, so the key
    mapping is mechanical (docstring contract in the module header).
    """
    plan = _hf_llama_plan(model, extra_layer_norms=extra_layer_norms,
                          layer_norms=layer_norms)
    tied_alias = set()
    if model.lm_head is not None:
        if "lm_head.weight" not in hf_state_dict:
            # tied-in-HF checkpoint feeding an untied model
            plan["lm_head.weight"] = ("model.embed_tokens.weight", True)
    else:
        # tied model: an HF checkpoint may still carry the lm_head alias of
        # the embedding — represented here through the tie, not a drop
        tied_alias.add("lm_head.weight")

    # convert ONE tensor at a time (an 8B checkpoint converted wholesale
    # would double peak host memory) and remap; set_state_dict then reuses
    # the framework's shape-checked, dtype-cast assignment
    mapped, consumed = {}, set()
    for name, (hf_key, transpose) in plan.items():
        if hf_key not in hf_state_dict:
            raise KeyError(f"load_hf_llama: checkpoint is missing {hf_key!r}")
        v = _hf_to_np(hf_state_dict[hf_key])
        mapped[name] = v.T if transpose else v
        consumed.add(hf_key)
    leftovers = [k for k in hf_state_dict
                 if k not in consumed and k not in tied_alias
                 and not k.endswith("rotary_emb.inv_freq")]
    if leftovers:
        raise ValueError(
            f"load_hf_llama: checkpoint tensors this model cannot represent "
            f"(silently dropping them would change logits): {leftovers[:5]}"
            f"{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected  # plan keys come from named_parameters
    if ignore_missing_prefixes:
        # multimodal wrappers (LLaVA) load their non-language submodules
        # through their own plan; those keys are legitimately absent here
        missing = [m for m in missing
                   if not m.startswith(tuple(ignore_missing_prefixes))]
    if missing:
        raise KeyError(f"load_hf_llama: model keys not covered: {missing[:5]}")
    return model


def _from_hf(config_cls, model_cls, hf_model_or_state, hf_config=None,
             extra_layer_norms=(), layer_norms=None, **config_overrides):
    """Shared HF-conversion protocol for the Llama-architecture families
    (Llama / Qwen2 / Mistral): unwrap model vs raw state, map the config,
    build, load."""
    import dataclasses as _dc

    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    base = hf_config_to_llama(hf_config, **config_overrides)
    cfg = base if config_cls is LlamaConfig else config_cls(**_dc.asdict(base))
    return load_hf_llama(model_cls(cfg), state,
                         extra_layer_norms=extra_layer_norms,
                         layer_norms=layer_norms)


def llama_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a LlamaForCausalLM from a transformers model (or a raw state
    dict + config): ``llama_from_hf(HFLlama.from_pretrained(...))``."""
    return _from_hf(LlamaConfig, LlamaForCausalLM, hf_model_or_state,
                    hf_config, **config_overrides)


def llama_to_hf(model, dtype=None):
    """Export to the HF Llama checkpoint layout (see export_hf_llama) —
    covers every family whose checkpoint IS the plain Llama key layout
    (Llama/Qwen2/Qwen3/Mistral/Gemma; Gemma2 adds its sandwich norms).
    Families whose conversion TRANSFORMS the checkpoint (Phi-3 fuses
    projections, GLM de-interleaves rotary rows) REFUSE — exporting their
    runtime weights under HF keys without reversing the transform would
    emit a silently wrong checkpoint. Parameter dtypes are preserved
    (``dtype`` forces a uniform cast — see export_hf_llama)."""
    from .gemma2 import Gemma2ForCausalLM
    from .glm import GlmForCausalLM
    from .olmo2 import _OLMO2_NORMS, Olmo2ForCausalLM
    from .phi3 import Phi3ForCausalLM

    if isinstance(model, (GlmForCausalLM, Phi3ForCausalLM)):
        raise NotImplementedError(
            f"llama_to_hf: {type(model).__name__} checkpoints are "
            "TRANSFORMED at load (fused projections / interleaved "
            "rotary); the reverse transform is not implemented — "
            "exporting raw runtime weights would be silently wrong")
    extra, norms = (), None
    if isinstance(model, Gemma2ForCausalLM):
        extra = ("pre_feedforward_layernorm", "post_feedforward_layernorm")
    if isinstance(model, Olmo2ForCausalLM):
        norms = _OLMO2_NORMS
    return export_hf_llama(model, extra_layer_norms=extra,
                           layer_norms=norms, dtype=dtype)
