"""Qwen3 decoder family.

Role parity: the reference serves the Qwen line through PaddleNLP's qwen
modeling on the same fleet stack as its llama modeling; Qwen3 is that
recipe with two signature deviations this build expresses as LlamaConfig
knobs, so every path (training, hybrid parallel, serving, HF interop) is
the already-tested Llama machinery:

- ``qk_norm=True``: per-head RMSNorm on q/k after projection, before RoPE
  (replaces Qwen2's q/k/v biases — Qwen3 is bias-free);
- ``head_dim`` decoupled from hidden_size/num_heads (e.g. Qwen3-4B:
  hidden 2560, 32 heads, head_dim 128).
"""
from __future__ import annotations

import dataclasses

from .llama import LlamaConfig, LlamaForCausalLM, _from_hf


@dataclasses.dataclass
class Qwen3Config(LlamaConfig):
    vocab_size: int = 151936
    hidden_size: int = 4096
    intermediate_size: int = 12288
    num_hidden_layers: int = 36
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    head_dim: int | None = 128
    max_position_embeddings: int = 40960
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6
    attention_bias: bool = False
    qk_norm: bool = True                 # the Qwen3 signature deviation

    @staticmethod
    def qwen3_8b(**kw):
        return Qwen3Config(**kw)

    @staticmethod
    def qwen3_4b(**kw):
        # head_dim 128 with hidden/heads = 80: the decoupled case
        base = dict(hidden_size=2560, intermediate_size=9728,
                    num_hidden_layers=36, num_attention_heads=32,
                    num_key_value_heads=8, tie_word_embeddings=True)
        base.update(kw)
        return Qwen3Config(**base)

    @staticmethod
    def tiny(**kw):
        # head_dim 32 != hidden/heads (16): the decoupling is exercised
        # by every tiny-config test
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, head_dim=32,
                    max_position_embeddings=256, dtype="float32")
        base.update(kw)
        return Qwen3Config(**base)


class Qwen3ForCausalLM(LlamaForCausalLM):
    """Qwen3 causal LM — Llama decoder with per-head q/k RMSNorm and a
    decoupled head width."""

    def __init__(self, config: Qwen3Config):
        if config.qk_norm not in (True, "per_head"):
            raise ValueError(
                "Qwen3 uses PER-HEAD q/k norms (qk_norm=True); "
                f"got qk_norm={config.qk_norm!r}")
        super().__init__(config)


def qwen3_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a Qwen3ForCausalLM from a transformers Qwen3 model (or a raw
    state dict + config)."""
    config_overrides.setdefault("qk_norm", True)
    return _from_hf(Qwen3Config, Qwen3ForCausalLM, hf_model_or_state,
                    hf_config, **config_overrides)
