"""ERNIE-4.5 — Baidu's flagship decoder family (BASELINE.json config 2).

The 4.5 generation is a heterogeneous-MoE causal LM (GQA attention, RoPE,
SwiGLU, RMSNorm; routed experts with always-on shared experts and top-k
softmax-renormalized gating). This module provides the text-expert slice of
that design on the repo's MoE decoder machinery (``models.llama_moe`` —
grouped-GEMM experts, EP sharding over the hybrid mesh, dense GShard
dispatch); the multimodal vision-expert branch is out of scope for a
text-pretraining framework (the reference platform trains it through
separate PaddleMIX tooling).

Role anchors: the reference serves this family with the same fused-MoE
kernel stack as DeepSeekMoE (paddle/phi/kernels/fusion/cutlass/
fused_moe_kernel.cu, moe_gate_dispatch SPMD rule); the architecture knobs
below follow the published open-release configs (e.g. the 21B-A3B text
model: 28 layers, 64 routed experts, top-6, 2 shared experts).
"""
from __future__ import annotations

import dataclasses

from .llama_moe import LlamaMoEConfig, LlamaMoEForCausalLM


@dataclasses.dataclass
class Ernie45Config(LlamaMoEConfig):
    """ERNIE-4.5 text-model knobs on the MoE decoder base."""

    n_routed_experts: int = 64
    n_shared_experts: int = 2
    num_experts_per_tok: int = 6
    norm_topk_prob: bool = True       # softmax renorm over the selected k
    first_k_dense_replace: int = 1    # leading dense layer(s)
    moe_correction_bias: bool = True  # aux-free balancing bias (the HF
    # checkpoint's moe_statics.e_score_correction_bias) steers top-k
    # SELECTION; combine weights stay the raw softmax probs
    router_aux_loss_coef: float = 0.001

    @staticmethod
    def a3b(**kw):
        """The 21B-A3B open-release shape (text experts)."""
        base = dict(vocab_size=103424, hidden_size=2560,
                    intermediate_size=12288, num_hidden_layers=28,
                    num_attention_heads=20, num_key_value_heads=4,
                    max_position_embeddings=131072,
                    moe_intermediate_size=1536)
        base.update(kw)
        return Ernie45Config(**base)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=3, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32", n_routed_experts=4,
                    num_experts_per_tok=2, moe_intermediate_size=64,
                    n_shared_experts=1, first_k_dense_replace=1)
        base.update(kw)
        return Ernie45Config(**base)


class Ernie45ForCausalLM(LlamaMoEForCausalLM):
    """ERNIE-4.5-style causal LM: the MoE decoder with shared experts.

    Inherits training (aux-balanced router loss), EP sharding, KV-cache
    decode, and the serving paths unchanged from the MoE base."""

    def __init__(self, config: Ernie45Config):
        super().__init__(config)


def _hf_config_to_ernie45(hf_config, **overrides) -> Ernie45Config:
    from .llama import _hf_get

    get = _hf_get(hf_config)
    if get("use_bias", False):
        raise NotImplementedError(
            "ernie45_from_hf: use_bias=True checkpoints are not "
            "represented (the 4.5 text releases ship bias-free)")
    end = get("moe_layer_end_index", -1)
    layers = get("num_hidden_layers")
    if end not in (-1, None) and end < layers - 1:
        raise NotImplementedError(
            "ernie45_from_hf: trailing dense layers "
            f"(moe_layer_end_index={end} < {layers - 1}) are not "
            "representable; only leading dense layers map")
    if get("moe_layer_interval", 1) != 1:
        raise NotImplementedError(
            "ernie45_from_hf: moe_layer_interval != 1 (dense layers "
            "interleaved mid-stack) is not representable; only leading "
            "dense layers (moe_layer_start_index) map")
    kw = dict(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads"),
        max_position_embeddings=get("max_position_embeddings"),
        rms_norm_eps=get("rms_norm_eps", 1e-5),
        rope_theta=get("rope_theta", 500000.0),
        tie_word_embeddings=bool(get("tie_word_embeddings", True)),
        n_routed_experts=get("moe_num_experts"),
        num_experts_per_tok=get("moe_k"),
        moe_intermediate_size=get("moe_intermediate_size"),
        n_shared_experts=get("moe_num_shared_experts"),
        first_k_dense_replace=get("moe_layer_start_index", 1),
    )
    kw.update(overrides)
    return Ernie45Config(**kw)


def load_hf_ernie45(model: "Ernie45ForCausalLM",
                    hf_state_dict) -> "Ernie45ForCausalLM":
    """Pack a transformers Ernie4_5_MoeForCausalLM state dict: per-expert
    gate/up/down stack into the grouped [E, ...] layout, the router and
    its aux-free correction bias map onto gate_weight /
    e_score_correction_bias, leading dense layers load as plain MLPs."""
    import numpy as np

    from .llama import _hf_to_np

    cfg = model.config
    E, L = cfg.n_routed_experts, cfg.num_hidden_layers
    dense_upto = cfg.first_k_dense_replace
    mapped, consumed = {}, set()

    def take(hf_key, transpose):
        if hf_key not in hf_state_dict:
            raise KeyError(f"load_hf_ernie45: missing {hf_key!r}")
        consumed.add(hf_key)
        v = _hf_to_np(hf_state_dict[hf_key])
        return v.T if transpose else v

    head_dim = cfg.hidden_size // cfg.num_attention_heads

    def take_rope_proj(hf_key, n_heads):
        """ERNIE-4.5 applies INTERLEAVED (NeoX rotate-every-two) rotary;
        this model applies the llama half-rotate convention. The two are
        exactly equivalent under an even-then-odd reorder of each head's
        projection rows (the Meta->HF llama converter's permutation), so
        the checkpoint is converted rather than the kernel forked."""
        w = _hf_to_np(hf_state_dict[hf_key])      # torch [out, in]
        consumed.add(hf_key)
        out_dim, in_dim = w.shape
        wh = w.reshape(n_heads, head_dim, in_dim)
        wh = np.concatenate([wh[:, 0::2], wh[:, 1::2]], axis=1)
        return wh.reshape(out_dim, in_dim).T      # -> [in, out]

    mapped["llama.embed_tokens.weight"] = take("model.embed_tokens.weight",
                                               False)
    mapped["llama.norm.weight"] = take("model.norm.weight", False)
    if model.lm_head is not None:
        src = ("lm_head.weight" if "lm_head.weight" in hf_state_dict
               else "model.embed_tokens.weight")
        mapped["lm_head.weight"] = take(src, True)
    for i in range(L):
        hf, ours = f"model.layers.{i}", f"llama.layers.{i}"
        mapped[f"{ours}.self_attn.q_proj.weight"] = take_rope_proj(
            f"{hf}.self_attn.q_proj.weight", cfg.num_attention_heads)
        mapped[f"{ours}.self_attn.k_proj.weight"] = take_rope_proj(
            f"{hf}.self_attn.k_proj.weight", cfg.num_key_value_heads)
        for proj in ("v_proj", "o_proj"):
            mapped[f"{ours}.self_attn.{proj}.weight"] = take(
                f"{hf}.self_attn.{proj}.weight", True)
        mapped[f"{ours}.input_layernorm.weight"] = take(
            f"{hf}.input_layernorm.weight", False)
        mapped[f"{ours}.post_attention_layernorm.weight"] = take(
            f"{hf}.post_attention_layernorm.weight", False)
        if i < dense_upto:
            for proj in ("gate_proj", "up_proj", "down_proj"):
                mapped[f"{ours}.mlp.{proj}.weight"] = take(
                    f"{hf}.mlp.{proj}.weight", True)
            continue
        mapped[f"{ours}.mlp.gate_weight"] = take(f"{hf}.mlp.gate.weight",
                                                 True)
        mapped[f"{ours}.mlp.e_score_correction_bias"] = take(
            f"{hf}.mlp.moe_statics.e_score_correction_bias",
            False).reshape(E)
        from .llama_moe import pack_hf_experts

        (mapped[f"{ours}.mlp.experts.w1"],
         mapped[f"{ours}.mlp.experts.b1"],
         mapped[f"{ours}.mlp.experts.w2"],
         mapped[f"{ours}.mlp.experts.b2"]) = pack_hf_experts(
            take, f"{hf}.mlp", E, cfg.hidden_size)
        for proj in ("gate_proj", "up_proj", "down_proj"):
            mapped[f"{ours}.mlp.shared_expert.{proj}.weight"] = take(
                f"{hf}.mlp.shared_experts.{proj}.weight", True)
    leftovers = [k for k in hf_state_dict
                 if k not in consumed and k != "lm_head.weight"
                 and not k.endswith("rotary_emb.inv_freq")]
    if leftovers:
        raise ValueError(
            f"load_hf_ernie45: checkpoint tensors this model cannot "
            f"represent: {leftovers[:5]}"
            f"{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected
    if missing:
        raise KeyError(f"load_hf_ernie45: model keys not covered: "
                       f"{missing[:5]}")
    return model


def ernie45_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build an Ernie45ForCausalLM from a transformers
    Ernie4_5_MoeForCausalLM (or raw state dict + config)."""
    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    cfg = _hf_config_to_ernie45(hf_config, **config_overrides)
    return load_hf_ernie45(Ernie45ForCausalLM(cfg), state)
