"""ERNIE-4.5 — Baidu's flagship decoder family (BASELINE.json config 2).

The 4.5 generation is a heterogeneous-MoE causal LM (GQA attention, RoPE,
SwiGLU, RMSNorm; routed experts with always-on shared experts and top-k
softmax-renormalized gating). This module provides the text-expert slice of
that design on the repo's MoE decoder machinery (``models.llama_moe`` —
grouped-GEMM experts, EP sharding over the hybrid mesh, dense GShard
dispatch); the multimodal vision-expert branch is out of scope for a
text-pretraining framework (the reference platform trains it through
separate PaddleMIX tooling).

Role anchors: the reference serves this family with the same fused-MoE
kernel stack as DeepSeekMoE (paddle/phi/kernels/fusion/cutlass/
fused_moe_kernel.cu, moe_gate_dispatch SPMD rule); the architecture knobs
below follow the published open-release configs (e.g. the 21B-A3B text
model: 28 layers, 64 routed experts, top-6, 2 shared experts).
"""
from __future__ import annotations

import dataclasses

from .llama_moe import LlamaMoEConfig, LlamaMoEForCausalLM


@dataclasses.dataclass
class Ernie45Config(LlamaMoEConfig):
    """ERNIE-4.5 text-model knobs on the MoE decoder base."""

    n_routed_experts: int = 64
    n_shared_experts: int = 2
    num_experts_per_tok: int = 6
    norm_topk_prob: bool = True       # softmax renorm over the selected k
    first_k_dense_replace: int = 1    # leading dense layer(s)
    router_aux_loss_coef: float = 0.001

    @staticmethod
    def a3b(**kw):
        """The 21B-A3B open-release shape (text experts)."""
        base = dict(vocab_size=103424, hidden_size=2560,
                    intermediate_size=12288, num_hidden_layers=28,
                    num_attention_heads=20, num_key_value_heads=4,
                    max_position_embeddings=131072,
                    moe_intermediate_size=1536)
        base.update(kw)
        return Ernie45Config(**base)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=3, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32", n_routed_experts=4,
                    num_experts_per_tok=2, moe_intermediate_size=64,
                    n_shared_experts=1, first_k_dense_replace=1)
        base.update(kw)
        return Ernie45Config(**base)


class Ernie45ForCausalLM(LlamaMoEForCausalLM):
    """ERNIE-4.5-style causal LM: the MoE decoder with shared experts.

    Inherits training (aux-balanced router loss), EP sharding, KV-cache
    decode, and the serving paths unchanged from the MoE base."""

    def __init__(self, config: Ernie45Config):
        super().__init__(config)
