"""Qwen2-MoE / Qwen1.5-MoE decoder family.

Role parity: the reference serves Qwen-MoE through PaddleNLP's qwen2_moe
modeling (BASELINE.json names "Qwen2-MoE EP" as a workload config). The
architecture is the LlamaMoE machinery specialized three ways: q/k/v
projection biases (the Qwen2 attention signature), a learned SIGMOID gate
scaling the shared expert's output (``shared_expert_gate``), and
no top-k renormalization (``norm_topk_prob=False`` — softmax over all
experts, top-k weights used as-is). Routed experts are SwiGLU GroupedMLPs
(fused gate‖up) shardable over the ep axis like every MoE family here.

``qwen2_moe_from_hf`` converts a transformers ``Qwen2MoeForCausalLM``
(per-expert gate/up/down projections are packed into the grouped [E, …]
layout; the [E, h] router and [1, h] shared gate transpose to the paddle
[in, out] convention).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .llama import _hf_get, mapped_rope_scaling
from .llama_moe import (LlamaMoEConfig, LlamaMoEForCausalLM,
                        load_hf_grouped_moe)


@dataclasses.dataclass
class Qwen2MoeConfig(LlamaMoEConfig):
    # Qwen1.5-MoE-A2.7B shape
    vocab_size: int = 151936
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    max_position_embeddings: int = 8192
    rope_theta: float = 1e6
    attention_bias: bool = True            # Qwen2 q/k/v biases
    n_routed_experts: int = 60
    num_experts_per_tok: int = 4
    moe_intermediate_size: int = 1408
    n_shared_experts: int = 4              # shared inter 5632 = 4 x 1408
    shared_expert_gate: bool = True        # sigmoid-gated shared expert
    norm_topk_prob: bool = False           # HF Qwen2MoeConfig default
    first_k_dense_replace: int = 0         # every layer is sparse

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32", n_routed_experts=4,
                    num_experts_per_tok=2, moe_intermediate_size=64,
                    n_shared_experts=2, first_k_dense_replace=0)
        base.update(kw)
        return Qwen2MoeConfig(**base)


class Qwen2MoeForCausalLM(LlamaMoEForCausalLM):
    """Qwen2-MoE causal LM — LlamaMoE decoder with q/k/v biases and the
    sigmoid shared-expert gate."""

    def __init__(self, config: Qwen2MoeConfig):
        if not config.attention_bias:
            raise ValueError("Qwen2-MoE uses attention_bias=True")
        if not config.shared_expert_gate and config.n_shared_experts > 0:
            raise ValueError("Qwen2-MoE gates its shared expert "
                             "(shared_expert_gate=True)")
        super().__init__(config)


def _hf_config_to_qwen2_moe(hf_config, **overrides) -> Qwen2MoeConfig:
    get = _hf_get(hf_config)
    if get("decoder_sparse_step", 1) != 1 or get("mlp_only_layers", []):
        raise NotImplementedError(
            "qwen2_moe_from_hf: mixed sparse/dense layer patterns "
            "(decoder_sparse_step != 1 or mlp_only_layers) are not "
            "representable; this build supports uniformly-sparse stacks")
    shared_inter = get("shared_expert_intermediate_size")
    moe_inter = get("moe_intermediate_size")
    if not shared_inter or not moe_inter:
        raise KeyError(
            "qwen2_moe_from_hf: config must carry positive "
            "moe_intermediate_size and shared_expert_intermediate_size "
            f"(got {moe_inter!r} / {shared_inter!r})")
    if shared_inter % moe_inter:
        raise NotImplementedError(
            f"shared_expert_intermediate_size ({shared_inter}) must be a "
            f"multiple of moe_intermediate_size ({moe_inter})")
    kw = dict(
        rope_scaling=mapped_rope_scaling(get),
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads"),
        max_position_embeddings=get("max_position_embeddings"),
        rms_norm_eps=get("rms_norm_eps", 1e-6),
        rope_theta=get("rope_theta", 1e6),
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
        n_routed_experts=get("num_experts"),
        num_experts_per_tok=get("num_experts_per_tok"),
        moe_intermediate_size=moe_inter,
        n_shared_experts=shared_inter // moe_inter,
        norm_topk_prob=bool(get("norm_topk_prob", False)),
        router_aux_loss_coef=get("router_aux_loss_coef", 0.001),
    )
    kw.update(overrides)
    return Qwen2MoeConfig(**kw)


def load_hf_qwen2_moe(model: Qwen2MoeForCausalLM,
                      hf_state_dict) -> Qwen2MoeForCausalLM:
    """Pack a transformers Qwen2MoeForCausalLM state dict into the grouped
    layout (shared loader; q/k/v biases + sigmoid-gated shared expert)."""
    return load_hf_grouped_moe(model, hf_state_dict, attn_biases=True,
                               shared_expert=True, shared_gate=True,
                               who="load_hf_qwen2_moe")


def qwen2_moe_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a Qwen2MoeForCausalLM from a transformers model (or raw state
    dict + config)."""
    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    cfg = _hf_config_to_qwen2_moe(hf_config, **config_overrides)
    return load_hf_qwen2_moe(Qwen2MoeForCausalLM(cfg), state)
