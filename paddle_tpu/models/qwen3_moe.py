"""Qwen3-MoE decoder family (Qwen3-30B-A3B class).

Role parity: the reference's Qwen-MoE serving recipe (BASELINE.json names
"Qwen2-MoE EP"), current generation. The architecture is the LlamaMoE
machinery with the Qwen3 attention signature — per-head q/k RMSNorm
(``qk_norm``), bias-free projections, ``head_dim`` decoupled from
hidden/heads — and a plain routed MoE FFN: NO shared expert, softmax
router with renormalized top-k (``norm_topk_prob=True``). Routed experts
are SwiGLU GroupedMLPs (fused gate‖up) shardable over the ep axis like
every MoE family here.
"""
from __future__ import annotations

import dataclasses

from .llama import _hf_get, mapped_rope_scaling
from .llama_moe import (LlamaMoEConfig, LlamaMoEForCausalLM,
                        load_hf_grouped_moe)


@dataclasses.dataclass
class Qwen3MoeConfig(LlamaMoEConfig):
    # Qwen3-30B-A3B shape
    vocab_size: int = 151936
    hidden_size: int = 2048
    intermediate_size: int = 6144
    num_hidden_layers: int = 48
    num_attention_heads: int = 32
    num_key_value_heads: int = 4
    head_dim: int | None = 128             # decoupled (quotient is 64)
    max_position_embeddings: int = 40960
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6
    attention_bias: bool = False
    qk_norm: bool = True                    # the Qwen3 attention signature
    n_routed_experts: int = 128
    num_experts_per_tok: int = 8
    moe_intermediate_size: int = 768
    n_shared_experts: int = 0               # no shared expert in Qwen3-MoE
    norm_topk_prob: bool = True
    first_k_dense_replace: int = 0          # every layer is sparse

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, head_dim=32,
                    max_position_embeddings=256, dtype="float32",
                    n_routed_experts=4, num_experts_per_tok=2,
                    moe_intermediate_size=32, n_shared_experts=0,
                    first_k_dense_replace=0)
        base.update(kw)
        return Qwen3MoeConfig(**base)


class Qwen3MoeForCausalLM(LlamaMoEForCausalLM):
    """Qwen3-MoE causal LM — LlamaMoE decoder with the Qwen3 attention
    signature and a shared-expert-free routed FFN."""

    def __init__(self, config: Qwen3MoeConfig):
        if config.qk_norm not in (True, "per_head"):
            raise ValueError(
                "Qwen3-MoE uses PER-HEAD q/k norms (qk_norm=True); "
                f"got qk_norm={config.qk_norm!r}")
        if config.n_shared_experts:
            raise ValueError("Qwen3-MoE has no shared expert "
                             "(n_shared_experts=0)")
        super().__init__(config)


def _hf_config_to_qwen3_moe(hf_config, **overrides) -> Qwen3MoeConfig:
    get = _hf_get(hf_config)
    if get("decoder_sparse_step", 1) != 1 or get("mlp_only_layers", []):
        raise NotImplementedError(
            "qwen3_moe_from_hf: mixed sparse/dense layer patterns "
            "(decoder_sparse_step != 1 or mlp_only_layers) are not "
            "representable; this build supports uniformly-sparse stacks")
    kw = dict(
        # a yarn-scaled long-context checkpoint is config-only — validate
        # and MAP it rather than silently building plain-RoPE tables
        rope_scaling=mapped_rope_scaling(get),
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads"),
        head_dim=get("head_dim"),
        max_position_embeddings=get("max_position_embeddings"),
        rms_norm_eps=get("rms_norm_eps", 1e-6),
        rope_theta=get("rope_theta", 1e6),
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
        n_routed_experts=get("num_experts"),
        num_experts_per_tok=get("num_experts_per_tok"),
        moe_intermediate_size=get("moe_intermediate_size"),
        # False mirrors the HF Qwen3MoeConfig class default for configs
        # that omit the key (shipped checkpoints set it explicitly)
        norm_topk_prob=bool(get("norm_topk_prob", False)),
        router_aux_loss_coef=get("router_aux_loss_coef", 0.001),
    )
    kw.update(overrides)
    return Qwen3MoeConfig(**kw)


def load_hf_qwen3_moe(model: Qwen3MoeForCausalLM,
                      hf_state_dict) -> Qwen3MoeForCausalLM:
    """Pack a transformers Qwen3MoeForCausalLM state dict into the grouped
    layout (shared loader; q/k per-head norms, no biases, no shared
    expert)."""
    return load_hf_grouped_moe(model, hf_state_dict, qk_norms=True,
                               who="load_hf_qwen3_moe")


def qwen3_moe_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a Qwen3MoeForCausalLM from a transformers model (or raw state
    dict + config)."""
    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    cfg = _hf_config_to_qwen3_moe(hf_config, **config_overrides)
    return load_hf_qwen3_moe(Qwen3MoeForCausalLM(cfg), state)
