"""ERNIE — Baidu's flagship encoder family (BASELINE.json config 2).

Reference anchors: the ERNIE model family the reference platform trains
(PaddleNLP ernie modeling; the framework-side pieces are the transformer
stack python/paddle/nn/layer/transformer.py and fused attention kernels).
Architecture = BERT-style bidirectional encoder: word + position +
token-type embeddings → LayerNorm/dropout → N TransformerEncoder layers
(post-norm, GELU) → pooler; heads for masked-LM, sequence classification,
and pretraining (MLM + NSP).

TPU-native: built entirely from paddle_tpu.nn blocks — every matmul is an
XLA dot on the MXU, the encoder runs under jit/train_step unchanged, and
GSPMD shards batch/hidden via the usual mesh annotations.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from .. import nn
from ..nn.layer import Layer
from ..nn.initializer import Normal
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap


@dataclasses.dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    dtype: str = "float32"

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=64, type_vocab_size=2)
        base.update(kw)
        return ErnieConfig(**base)


class ErnieEmbeddings(Layer):
    """word + position + token_type embeddings (+ LN + dropout)."""

    def __init__(self, config: ErnieConfig):
        super().__init__(dtype=config.dtype)
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = wrap(jnp.arange(s, dtype=jnp.int32)[None, :])
        if token_type_ids is None:
            token_type_ids = wrap(jnp.zeros(
                (input_ids.shape[0], s), jnp.int32))
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class ErnieModel(Layer):
    """Bidirectional encoder + tanh pooler over [CLS]."""

    def __init__(self, config: ErnieConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size,
            dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            normalize_before=False)
        self.encoder = nn.TransformerEncoder(layer, config.num_hidden_layers)
        self.pooler_dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is None:
            # mask pad tokens (paddle ernie builds this from pad_token_id)
            am = apply(
                "ernie_pad_mask",
                lambda ids: (ids != self.config.pad_token_id), input_ids,
                differentiable=False)
        else:
            am = attention_mask
        # additive [B, 1, 1, S] mask for MultiHeadAttention
        addmask = apply(
            "ernie_additive_mask",
            lambda m: jnp.where(m[:, None, None, :].astype(bool), 0.0,
                                -1e9).astype(jnp.float32),
            am, differentiable=False)
        hidden = self.embeddings(input_ids, token_type_ids, position_ids)
        hidden = self.encoder(hidden, src_mask=addmask)
        pooled = apply("ernie_pool", lambda h, w, b: jnp.tanh(
            h[:, 0] @ w + b), hidden, self.pooler_dense.weight,
            self.pooler_dense.bias)
        return hidden, pooled


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__(dtype=config.dtype)
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, labels=None, **kw):
        _, pooled = self.ernie(input_ids, token_type_ids, **kw)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        loss = nn.CrossEntropyLoss()(logits, labels)
        return loss, logits


class ErnieLMHead(Layer):
    """Transform + decode to vocab, weights tied to the word embeddings."""

    def __init__(self, config: ErnieConfig, embedding_weights):
        super().__init__(dtype=config.dtype)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self._tied = embedding_weights
        self.decoder_bias = self.create_parameter(
            [config.vocab_size],
            default_initializer=nn.initializer.Constant(0.0), is_bias=True)
        self.act = config.hidden_act

    def forward(self, hidden):
        h = self.layer_norm(getattr(nn.functional, self.act)(
            self.transform(hidden)))
        return apply("ernie_mlm_logits",
                     lambda x, w, b: x @ w.T + b, h, self._tied,
                     self.decoder_bias)


class ErnieForMaskedLM(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.ernie = ErnieModel(config)
        self.cls = ErnieLMHead(config,
                               self.ernie.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, labels=None, **kw):
        hidden, _ = self.ernie(input_ids, token_type_ids, **kw)
        logits = self.cls(hidden)
        if labels is None:
            return logits

        loss = apply("ernie_mlm_loss", _mlm_loss, logits, labels)
        return loss, logits


def _mlm_loss(lg, lb):
    """Masked-token cross entropy; positions with label < 0 are ignored."""
    import jax

    logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logp, jnp.maximum(lb, 0).astype(jnp.int32)[..., None], -1)[..., 0]
    mask = (lb >= 0)
    return -(picked * mask).sum() / jnp.maximum(mask.sum(), 1)


class ErnieForPretraining(Layer):
    """MLM + next-sentence heads (the classic pretraining objective)."""

    def __init__(self, config: ErnieConfig):
        super().__init__(dtype=config.dtype)
        self.mlm = ErnieForMaskedLM(config)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, mlm_labels=None,
                nsp_labels=None, **kw):
        hidden, pooled = self.mlm.ernie(input_ids, token_type_ids, **kw)
        mlm_logits = self.mlm.cls(hidden)
        nsp_logits = self.nsp(pooled)
        if mlm_labels is None:
            return mlm_logits, nsp_logits
        loss = apply("ernie_mlm_loss", _mlm_loss, mlm_logits, mlm_labels)
        if nsp_labels is not None:
            loss = loss + nn.CrossEntropyLoss()(nsp_logits, nsp_labels)
        return loss, mlm_logits, nsp_logits
