"""GPT-2 decoder family (learned positions, pre-LN, gelu MLP).

Role parity: the GPT family is the reference ecosystem's classic
pretraining flagship (the fleet GPT-3 recipes); architecturally it is the
pre-RoPE decoder class — learned absolute position embeddings, LayerNorm
with bias, fused qkv projection, tanh-approx gelu, tied lm head.

TPU-native design: the blocks reuse this build's cached-decode machinery
(generation.cached_attention and every downstream path: jitted prefill,
scan decode, paged serving, beam search) by feeding it IDENTITY rotation
tables — RoPE with cos=1/sin=0 is the identity, so position information
rides the wpe embedding exactly as GPT-2 defines it while the KV cache
layout, ragged masks, and per-row position bookkeeping stay shared.

HF interop note: transformers GPT-2 stores projection weights as Conv1D
[in, out] — the SAME layout as this build's Linear — so conversion is
transpose-free (unlike the Llama families).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn.layer import Layer
from ..nn.initializer import Normal
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap
from .llama import _hf_get, causal_lm_loss, tied_lm_head_logits


@dataclasses.dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None      # default 4*hidden
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    # MHA: the shared cache machinery reads num_key_value_heads
    @property
    def num_key_value_heads(self):
        return self.num_attention_heads

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=256,
                    dtype="float32")
        base.update(kw)
        return GPT2Config(**base)


class GPT2Attention(Layer):
    """Fused-qkv causal self-attention with biases (the c_attn/c_proj
    pair); decode rides the shared cached_attention with identity RoPE."""

    def __init__(self, config: GPT2Config):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.config = config
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        with dtype_guard(config.dtype):
            self.c_attn = nn.Linear(h, 3 * h)
            self.c_proj = nn.Linear(h, h)

    def forward(self, hidden, identity_rope, attention_mask=None,
                kv_cache=None):
        b, s = hidden.shape[0], hidden.shape[1]
        h, d = self.num_heads, self.head_dim
        qkv = self.c_attn(hidden)
        q, k, v = (t.reshape([b, s, h, d]) for t in
                   (qkv[:, :, : h * d], qkv[:, :, h * d: 2 * h * d],
                    qkv[:, :, 2 * h * d:]))
        cos, sin = identity_rope
        cfg = self.config

        if isinstance(kv_cache, dict):
            from ..generation import cached_attention, paged_cached_attention

            if "k_pages" in kv_cache:
                out, kp, vp = apply(
                    "gpt2_attention_paged", paged_cached_attention,
                    q, k, v, cos, sin, kv_cache["k_pages"],
                    kv_cache["v_pages"], kv_cache["page_indices"],
                    kv_cache["lengths"], kv_cache.get("page_size"))
                new = dict(kv_cache)
                new.update(k_pages=kp, v_pages=vp,
                           lengths=kv_cache["lengths"] + s)
                return self.c_proj(out.reshape([b, s, h * d])), new
            out, k_buf, v_buf = apply(
                "gpt2_attention_cached", cached_attention, q, k, v, cos, sin,
                kv_cache["k"], kv_cache["v"], kv_cache["pos"],
                kv_cache.get("allowed"), kv_cache.get("row_pos"),
                use_flash=cfg.use_flash_attention,
                prefill=bool(kv_cache.get("prefill", False)))
            new = {"k": k_buf, "v": v_buf, "pos": kv_cache["pos"] + s}
            for key in ("allowed",):
                if key in kv_cache:
                    new[key] = kv_cache[key]
            if "row_pos" in kv_cache:
                new["row_pos"] = kv_cache["row_pos"] + s
            return self.c_proj(out.reshape([b, s, h * d])), new

        def attn_fn(q, k, v):
            from ..nn.functional.attention import _sdpa_ref
            from ..ops.pallas import flash_attention as pf

            if cfg.use_flash_attention and pf.supported(q, k, v):
                return pf.flash_attention_bshd(q, k, v, causal=True)
            return _sdpa_ref(q, k, v, causal=True)

        out = apply("gpt2_attention", attn_fn, q, k, v)
        return self.c_proj(out.reshape([b, s, h * d]))


class GPT2MLP(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        with dtype_guard(config.dtype):
            self.c_fc = nn.Linear(config.hidden_size, config.intermediate_size)
            self.c_proj = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x):
        # gelu_new (tanh approximation) — the GPT-2 activation
        act = apply("gelu_tanh", lambda a: jax.nn.gelu(a, approximate=True),
                    self.c_fc(x))
        return self.c_proj(act)


class GPT2Block(Layer):
    """Pre-LN residual block: x + attn(ln_1(x)); x + mlp(ln_2(x))."""

    def __init__(self, config: GPT2Config):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        with dtype_guard(config.dtype):
            self.ln_1 = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_epsilon)
            self.ln_2 = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_epsilon)
        self.attn = GPT2Attention(config)
        self.mlp = GPT2MLP(config)

    def forward(self, hidden, identity_rope, attention_mask=None,
                kv_cache=None):
        if kv_cache is not None:
            a, kv_cache = self.attn(self.ln_1(hidden), identity_rope,
                                    attention_mask, kv_cache)
            hidden = hidden + a
            hidden = hidden + self.mlp(self.ln_2(hidden))
            return hidden, kv_cache
        hidden = hidden + self.attn(self.ln_1(hidden), identity_rope,
                                    attention_mask)
        return hidden + self.mlp(self.ln_2(hidden))


class GPT2Model(Layer):
    """wte + wpe embeddings → pre-LN blocks → ln_f. Exposes the cached
    decode contract (forward_cached) the generation/serving stack drives."""

    def __init__(self, config: GPT2Config):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.config = config
        with dtype_guard(config.dtype):
            self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
            self.wpe = nn.Embedding(config.max_position_embeddings,
                                    config.hidden_size)
            self.ln_f = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_epsilon)
        for emb in (self.wte, self.wpe):
            emb.weight._array = (
                Normal(0.0, config.initializer_range)(
                    tuple(emb.weight.shape), jnp.float32)
                .astype(emb.weight.dtype))
        self.h = nn.LayerList([GPT2Block(config)
                               for _ in range(config.num_hidden_layers)])
        # GPT-2 init recipe: every projection N(0, initializer_range); the
        # residual-stream projections (c_proj) scaled by 1/sqrt(2*n_layer)
        # ("Scale initialized weights of residual layers", GPT-2 paper)
        import math

        resid_std = config.initializer_range / math.sqrt(
            2 * config.num_hidden_layers)
        for name, p in self.named_parameters():
            if name.endswith("c_proj.weight"):
                std = resid_std
            elif name.endswith((".weight",)) and ("c_attn" in name
                                                  or "c_fc" in name):
                std = config.initializer_range
            else:
                continue
            p._array = (Normal(0.0, std)(tuple(p.shape), jnp.float32)
                        .astype(p.dtype))
        self._rope_cache = {}

    def _identity_rope(self, length):
        """cos=1 / sin=0 tables: RoPE becomes the identity, so the shared
        cache machinery runs unrotated GPT-2 attention."""
        if length not in self._rope_cache:
            d = self.config.hidden_size // self.config.num_attention_heads
            # concrete numpy constants: this may be first called INSIDE a
            # jit trace, and caching a traced jnp.ones would leak the tracer
            self._rope_cache[length] = (np.ones((length, d), np.float32),
                                        np.zeros((length, d), np.float32))
        cos, sin = self._rope_cache[length]
        # hand out jnp views (traced code indexes them with traced ids;
        # numpy would call __array__ on the tracer)
        return jnp.asarray(cos), jnp.asarray(sin)

    def _positions(self, s, caches):
        """Absolute positions for the current chunk: per-row (ragged) when
        the cache carries row_pos, else the shared scalar offset."""
        if caches and isinstance(caches[0], dict):
            c0 = caches[0]
            row_pos = c0.get("row_pos")
            if row_pos is None and "lengths" in c0:   # paged layout
                row_pos = c0["lengths"]
            if row_pos is not None:
                return row_pos[:, None] + jnp.arange(s)[None, :]
            return c0["pos"] + jnp.arange(s)
        return jnp.arange(s)

    def _embed(self, input_ids, positions):
        ids = unwrap(input_ids) if isinstance(input_ids, Tensor) else input_ids
        tok = unwrap(self.wte(wrap(ids)))
        wpe = unwrap(self.wpe.weight)
        pe = jnp.take(wpe, jnp.asarray(positions), axis=0)
        if pe.ndim == 2:           # [S, h] shared positions
            pe = pe[None]
        return wrap((tok + pe).astype(jnp.dtype(self.config.dtype)))

    def forward(self, input_ids, attention_mask=None):
        s = input_ids.shape[1]
        if s > self.config.max_position_embeddings:
            # learned position table is FIXED size (unlike RoPE tables);
            # out-of-range jnp.take would silently fill garbage embeddings
            raise ValueError(
                f"GPT2: sequence length {s} exceeds max_position_embeddings "
                f"{self.config.max_position_embeddings}")
        rope = self._identity_rope(s)
        hidden = self._embed(input_ids, jnp.arange(s))
        for block in self.h:
            hidden = block(hidden, rope, attention_mask)
        return self.ln_f(hidden)

    def forward_cached(self, input_ids, kv_caches, rope_len):
        # positions beyond the wpe table cannot occur here: every caller
        # bounds its worst-case length against max_position_embeddings
        # before allocating (generate() entry check, ContinuousBatchEngine
        # __init__, speculative._prefill) — the ADVICE r4 overflow concern
        # is closed at those entries, where the lengths are static
        s = input_ids.shape[1]
        rope = self._identity_rope(rope_len)
        hidden = self._embed(input_ids, self._positions(s, kv_caches))
        new_caches = []
        for block, cache in zip(self.h, kv_caches):
            hidden, c = block(hidden, rope, kv_cache=cache)
            new_caches.append(c)
        return self.ln_f(hidden), new_caches


class GPT2LMHeadModel(Layer):
    """GPT-2 causal LM with the tied wte head. The decoder module is
    installed at the ``llama`` attribute — the cached-decode contract slot
    every generation/serving path drives (``transformer`` aliases it)."""

    def __init__(self, config: GPT2Config):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.llama = GPT2Model(config)
        self.lm_head = None  # tied (paddle-side contract for a tied head)

    @property
    def transformer(self):
        return self.llama

    def lm_head_logits(self, hidden):
        return tied_lm_head_logits(hidden, self.llama.wte.weight)

    def forward(self, input_ids, labels=None, attention_mask=None):
        hidden = self.llama(input_ids, attention_mask)
        logits = self.lm_head_logits(hidden)
        if labels is None:
            return logits
        return causal_lm_loss(logits, labels), logits

    def generate(self, input_ids, **kw):
        from ..generation import generate as _generate

        return _generate(self, input_ids, **kw)

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


# ---------------------------------------------------------------------------
# HuggingFace checkpoint interop
# ---------------------------------------------------------------------------

def gpt2_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a GPT2LMHeadModel from a transformers GPT2LMHeadModel (or raw
    state dict + config). Conv1D weights are [in, out] — no transpose."""
    from .llama import _hf_to_np

    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    get = _hf_get(hf_config)
    kw = dict(vocab_size=get("vocab_size"),
              hidden_size=get("n_embd", get("hidden_size")),
              num_hidden_layers=get("n_layer", get("num_hidden_layers")),
              num_attention_heads=get("n_head", get("num_attention_heads")),
              max_position_embeddings=get("n_positions",
                                          get("max_position_embeddings")),
              layer_norm_epsilon=get("layer_norm_epsilon", 1e-5))
    kw.update(config_overrides)
    cfg = GPT2Config(**kw)
    model = GPT2LMHeadModel(cfg)

    plan = {"llama.wte.weight": "transformer.wte.weight",
            "llama.wpe.weight": "transformer.wpe.weight",
            "llama.ln_f.weight": "transformer.ln_f.weight",
            "llama.ln_f.bias": "transformer.ln_f.bias"}
    for i in range(cfg.num_hidden_layers):
        hf, ours = f"transformer.h.{i}", f"llama.h.{i}"
        for mod, parts in (("ln_1", ("weight", "bias")),
                           ("ln_2", ("weight", "bias"))):
            for p in parts:
                plan[f"{ours}.{mod}.{p}"] = f"{hf}.{mod}.{p}"
        for mod in ("attn.c_attn", "attn.c_proj", "mlp.c_fc", "mlp.c_proj"):
            plan[f"{ours}.{mod}.weight"] = f"{hf}.{mod}.weight"
            plan[f"{ours}.{mod}.bias"] = f"{hf}.{mod}.bias"

    mapped, consumed = {}, set()
    for name, hf_key in plan.items():
        if hf_key not in state:
            raise KeyError(f"gpt2_from_hf: checkpoint is missing {hf_key!r}")
        mapped[name] = _hf_to_np(state[hf_key])
        consumed.add(hf_key)
    leftovers = [k for k in state
                 if k not in consumed and k != "lm_head.weight"
                 and not k.endswith(".attn.bias")          # causal mask buffer
                 and not k.endswith(".attn.masked_bias")]
    if leftovers:
        raise ValueError(
            f"gpt2_from_hf: checkpoint tensors this model cannot represent: "
            f"{leftovers[:5]}{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected
    if missing:
        raise KeyError(f"gpt2_from_hf: model keys not covered: {missing[:5]}")
    return model
