"""GLM / GLM-4 decoder families (glm-4-9b, GLM-4-0414 line).

Both are the Llama trunk with partial INTERLEAVED rotary and q/k/v
biases; GLM-4 additionally wraps each sublayer in the Gemma2-style
sandwich (a norm on the sublayer OUTPUT before the residual add), so its
trunk IS Gemma2Model with RMSNorm(1x) weights and silu MLPs — the
structure reuse is exact, only the checkpoint key names differ.

Rotary: GLM rotates the leading ``partial_rotary_factor`` slice of each
head in INTERLEAVED pair layout ((2i, 2i+1) share frequency i). This
build's kernels use the half-rotate layout, and the two are equivalent
under an even-then-odd reorder of each head's rotary projection rows —
the same de-interleave the ernie45/deepseek loaders do, here scoped to
the rotary slice (the pass-through tail stays in place). Conversion
permutes the checkpoint once; no kernel fork.

``glm_from_hf`` (transformers ``GlmForCausalLM``) and ``glm4_from_hf``
(``Glm4ForCausalLM``; fused gate_up split like phi3) convert with
logits/greedy parity.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .gemma2 import Gemma2Model
from .llama import (LlamaConfig, LlamaForCausalLM, _from_hf, _hf_get,
                    _hf_to_np, rope_dim_of)


@dataclasses.dataclass
class GlmConfig(LlamaConfig):
    # glm-4-9b shape
    vocab_size: int = 151552
    hidden_size: int = 4096
    intermediate_size: int = 13696
    num_hidden_layers: int = 40
    num_attention_heads: int = 32
    num_key_value_heads: int = 2
    head_dim: Optional[int] = 128
    max_position_embeddings: int = 131072
    rms_norm_eps: float = 1.5625e-07
    rope_theta: float = 10000.0
    attention_bias: bool = True
    partial_rotary_factor: float = 0.5

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, head_dim=16,
                    max_position_embeddings=256, dtype="float32")
        base.update(kw)
        return GlmConfig(**base)


@dataclasses.dataclass
class Glm4Config(GlmConfig):
    # GLM-4-0414 keeps the GLM attention signature; the block gains the
    # sandwich norms (Gemma2Model structure)
    pass


class GlmForCausalLM(LlamaForCausalLM):
    """GLM causal LM — Llama trunk with partial interleaved rotary
    (converted to half-rotate at load) and q/k/v biases."""

    def __init__(self, config: GlmConfig):
        if not config.attention_bias:
            raise ValueError("GLM uses attention_bias=True")
        if config.partial_rotary_factor >= 1.0:
            raise ValueError("GLM rotates a partial slice "
                             "(partial_rotary_factor < 1)")
        super().__init__(config)


class Glm4ForCausalLM(GlmForCausalLM):
    """GLM-4 causal LM — GLM attention on the sandwich-norm trunk."""

    model_cls = Gemma2Model


def deinterleave_rotary(w, n_heads, head_dim, rope_dim):
    """Even-then-odd reorder of each head's ROTARY rows (torch [out, ...]
    layout in, same layout out — works for weights and biases alike):
    interleaved-pair rotation == half-rotate rotation after this
    permutation; pass-through rows stay in place."""
    v = w.reshape((n_heads, head_dim) + w.shape[1:])
    rot = v[:, :rope_dim]
    rot = np.concatenate([rot[:, 0::2], rot[:, 1::2]], axis=1)
    return np.concatenate([rot, v[:, rope_dim:]], axis=1).reshape(w.shape)


def _translate_glm_state(state, hf_config, sandwich):
    """GLM checkpoint -> this build's key layout: q/k rotary rows
    de-interleaved, fused gate_up split, GLM-4 norm names mapped onto the
    Gemma2 sandwich attributes."""
    import types

    get = _hf_get(hf_config)
    heads = get("num_attention_heads")
    hd = get("head_dim") or get("hidden_size") // heads
    # THE runtime derivation: the permuted row set must equal the rotated
    # row set exactly, so the width comes from rope_dim_of itself
    rd = rope_dim_of(types.SimpleNamespace(
        head_dim=hd,
        partial_rotary_factor=(get("partial_rotary_factor") or 0.5)))
    kv = get("num_key_value_heads")

    renames = {}
    if sandwich:
        # ours <- GLM-4: post_attention(ours, on attn out) <-
        # post_self_attn; pre_feedforward <- post_attention;
        # post_feedforward <- post_mlp
        renames = {
            ".post_self_attn_layernorm.": ".post_attention_layernorm.",
            ".post_attention_layernorm.": ".pre_feedforward_layernorm.",
            ".post_mlp_layernorm.": ".post_feedforward_layernorm.",
        }
    out = {}
    for key, val in state.items():
        new_key = key
        for old, new in renames.items():
            if old in key:
                new_key = key.replace(old, new)
                break
        if key.endswith((".self_attn.q_proj.weight",
                         ".self_attn.q_proj.bias")):
            out[new_key] = deinterleave_rotary(_hf_to_np(val), heads, hd,
                                               rd)
        elif key.endswith((".self_attn.k_proj.weight",
                           ".self_attn.k_proj.bias")):
            out[new_key] = deinterleave_rotary(_hf_to_np(val), kv, hd, rd)
        elif key.endswith(".mlp.gate_up_proj.weight"):
            from .phi3 import split_gate_up

            split_gate_up(new_key, _hf_to_np(val), out)
        else:
            out[new_key] = val
    return out


def _glm_from_hf(config_cls, model_cls, sandwich, hf_model_or_state,
                 hf_config=None, **config_overrides):
    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    get = _hf_get(hf_config)
    config_overrides.setdefault(
        "partial_rotary_factor", float(get("partial_rotary_factor") or 0.5))
    extra = (("pre_feedforward_layernorm", "post_feedforward_layernorm")
             if sandwich else ())
    return _from_hf(config_cls, model_cls,
                    _translate_glm_state(state, hf_config, sandwich),
                    hf_config, extra_layer_norms=extra, **config_overrides)


def glm_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a GlmForCausalLM from a transformers Glm model (or a raw
    state dict + config)."""
    return _glm_from_hf(GlmConfig, GlmForCausalLM, False,
                        hf_model_or_state, hf_config, **config_overrides)


def glm4_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a Glm4ForCausalLM from a transformers Glm4 model (or a raw
    state dict + config)."""
    return _glm_from_hf(Glm4Config, Glm4ForCausalLM, True,
                        hf_model_or_state, hf_config, **config_overrides)
