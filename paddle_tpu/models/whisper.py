"""Whisper speech-recognition family (tiny → large-v3).

The audio member of the zoo — encoder-decoder ASR on the BART cache
machinery with Whisper's deviations:

- mel-spectrogram frontend: two Conv1Ds (the second stride-2) with gelu,
  then FIXED sinusoidal encoder positions (stored as a weight, matching
  the checkpoint layout);
- PRE-LN transformer blocks (BART is post-LN) and a final LayerNorm on
  both stacks;
- attention k_proj carries NO bias (q/v/out do);
- learned decoder positions indexed by absolute position (no BART +2
  offset), tied lm head (proj_out == embed weight).

The cached decode discipline (dense self-cache + precomputed cross K/V)
is models/bart.py's — WhisperAttention subclasses BartAttention for it.

``whisper_from_hf`` converts a transformers ``WhisperForConditionalGeneration``.
Parity is tested against manual HF greedy (transformers' whisper.generate
injects task/language forcing that belongs to the tokenizer layer, not
the model)."""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn.layer import Layer
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap
from .bart import BartAttention

# sentinel: "caller did not pass eos_token_id" — maps to the config
# default; an explicit None DISABLES eos (matching the decoder-only
# families' semantics)
_UNSET = object()

@dataclasses.dataclass
class WhisperConfig:
    # whisper-tiny shape
    vocab_size: int = 51865
    d_model: int = 384
    encoder_layers: int = 4
    decoder_layers: int = 4
    encoder_attention_heads: int = 6
    decoder_attention_heads: int = 6
    encoder_ffn_dim: int = 1536
    decoder_ffn_dim: int = 1536
    num_mel_bins: int = 80
    max_source_positions: int = 1500   # frames after the stride-2 conv
    max_target_positions: int = 448
    activation_function: str = "gelu"
    scale_embedding: bool = False
    decoder_start_token_id: int = 50257
    eos_token_id: int = 50256
    pad_token_id: int = 50256
    dtype: str = "float32"

    def __post_init__(self):
        if self.activation_function != "gelu":
            raise NotImplementedError(
                f"Whisper activation_function "
                f"{self.activation_function!r} is not supported (gelu "
                "only — every released Whisper checkpoint uses gelu)")

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, d_model=64, encoder_layers=2,
                    decoder_layers=2, encoder_attention_heads=4,
                    decoder_attention_heads=4, encoder_ffn_dim=128,
                    decoder_ffn_dim=128, num_mel_bins=8,
                    max_source_positions=16, max_target_positions=64,
                    decoder_start_token_id=1, eos_token_id=2,
                    pad_token_id=2)
        base.update(kw)
        return WhisperConfig(**base)


def sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's fixed encoder position table (modeling_whisper
    sinusoids): interleaved-free [sin | cos] halves over log-spaced
    timescales."""
    if channels % 2:
        raise ValueError("sinusoid channels must be even")
    log_inc = math.log(10000) / (channels // 2 - 1)
    inv = np.exp(-log_inc * np.arange(channels // 2, dtype=np.float64))
    t = np.arange(length, dtype=np.float64)[:, None] * inv[None, :]
    return np.concatenate([np.sin(t), np.cos(t)],
                          axis=1).astype(np.float32)


class WhisperAttention(BartAttention):
    """BART's cache-disciplined MHA with Whisper's bias layout: k_proj
    has no bias."""

    def __init__(self, config, n_heads: int):
        Layer.__init__(self, dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        d = config.d_model
        self.n_heads = n_heads
        self.head_dim = d // n_heads
        self.scale = 1.0 / math.sqrt(self.head_dim)
        with dtype_guard(config.dtype):
            self.q_proj = nn.Linear(d, d)
            self.k_proj = nn.Linear(d, d, bias_attr=False)
            self.v_proj = nn.Linear(d, d)
            self.out_proj = nn.Linear(d, d)


def _gelu(x):
    return jax.nn.gelu(x, approximate=False)


class WhisperEncoderLayer(Layer):
    """PRE-LN: x = x + attn(LN1(x)); x = x + ffn(LN2(x))."""

    def __init__(self, config: WhisperConfig):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.self_attn = WhisperAttention(config,
                                          config.encoder_attention_heads)
        with dtype_guard(config.dtype):
            self.self_attn_layer_norm = nn.LayerNorm(config.d_model)
            self.fc1 = nn.Linear(config.d_model, config.encoder_ffn_dim)
            self.fc2 = nn.Linear(config.encoder_ffn_dim, config.d_model)
            self.final_layer_norm = nn.LayerNorm(config.d_model)

    def forward(self, hidden):
        hidden = hidden + self.self_attn(self.self_attn_layer_norm(hidden))
        act = apply("gelu", _gelu, self.fc1(self.final_layer_norm(hidden)))
        return hidden + self.fc2(act)


class WhisperDecoderLayer(Layer):
    def __init__(self, config: WhisperConfig):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.self_attn = WhisperAttention(config,
                                          config.decoder_attention_heads)
        self.encoder_attn = WhisperAttention(config,
                                             config.decoder_attention_heads)
        with dtype_guard(config.dtype):
            self.self_attn_layer_norm = nn.LayerNorm(config.d_model)
            self.encoder_attn_layer_norm = nn.LayerNorm(config.d_model)
            self.fc1 = nn.Linear(config.d_model, config.decoder_ffn_dim)
            self.fc2 = nn.Linear(config.decoder_ffn_dim, config.d_model)
            self.final_layer_norm = nn.LayerNorm(config.d_model)

    def forward(self, hidden, enc_hidden=None, self_cache=None,
                cross_cache=None):
        h = self.self_attn_layer_norm(hidden)
        if self_cache is not None:
            a, self_cache = self.self_attn(h, kv_cache=self_cache)
        else:
            a = self.self_attn(h, causal=True)
        hidden = hidden + a
        h = self.encoder_attn_layer_norm(hidden)
        if cross_cache is not None:
            c, cross_cache = self.encoder_attn(h, kv_cache=cross_cache)
        else:
            c = self.encoder_attn(h, kv_hidden=enc_hidden)
        hidden = hidden + c
        act = apply("gelu", _gelu, self.fc1(self.final_layer_norm(hidden)))
        hidden = hidden + self.fc2(act)
        if self_cache is not None:
            return hidden, self_cache, cross_cache
        return hidden


class WhisperModel(Layer):
    def __init__(self, config: WhisperConfig):
        super().__init__(dtype=config.dtype)
        from ..framework.dtype import dtype_guard

        self.config = config
        d = config.d_model
        with dtype_guard(config.dtype):
            self.conv1 = nn.Conv1D(config.num_mel_bins, d, 3, padding=1)
            self.conv2 = nn.Conv1D(d, d, 3, stride=2, padding=1)
            self.embed_tokens = nn.Embedding(config.vocab_size, d)
            self.decoder_pos = nn.Embedding(config.max_target_positions, d)
            self.encoder_ln = nn.LayerNorm(d)
            self.decoder_ln = nn.LayerNorm(d)
            # fixed sinusoidal encoder positions, stored as a
            # (non-trainable) weight to match the checkpoint layout; the
            # table follows the model dtype — an f32 island here would
            # upcast every encoder activation at the stem
            self.encoder_pos = nn.Embedding(config.max_source_positions, d)
        self.encoder_pos.weight.set_value(
            sinusoids(config.max_source_positions, d).astype(config.dtype))
        self.encoder_pos.weight.stop_gradient = True
        self.encoder_layers_list = nn.LayerList(
            [WhisperEncoderLayer(config)
             for _ in range(config.encoder_layers)])
        self.decoder_layers_list = nn.LayerList(
            [WhisperDecoderLayer(config)
             for _ in range(config.decoder_layers)])
        self._scale = (math.sqrt(d) if config.scale_embedding else 1.0)

    def encode(self, input_features):
        """[B, num_mel_bins, T] mel frames -> [B, T//2, d_model]."""
        x = apply("gelu", _gelu, self.conv1(input_features))
        x = apply("gelu", _gelu, self.conv2(x))
        x = x.transpose([0, 2, 1])
        t = x.shape[1]
        if t > self.config.max_source_positions:
            raise ValueError(
                f"Whisper: {t} encoder frames exceed max_source_positions "
                f"{self.config.max_source_positions}")
        pe = jnp.take(unwrap(self.encoder_pos.weight), jnp.arange(t),
                      axis=0)
        hidden = wrap((unwrap(x) + pe).astype(jnp.dtype(self.config.dtype)))
        for layer in self.encoder_layers_list:
            hidden = layer(hidden)
        return self.encoder_ln(hidden)

    def _embed(self, ids, positions):
        tok = unwrap(self.embed_tokens(ids)) * self._scale
        pe = jnp.take(unwrap(self.decoder_pos.weight),
                      jnp.asarray(positions), axis=0)
        if pe.ndim == 2:
            pe = pe[None]
        return wrap((tok + pe).astype(jnp.dtype(self.config.dtype)))

    def decode(self, ids, enc_hidden):
        s = ids.shape[1]
        if s > self.config.max_target_positions:
            raise ValueError(
                f"Whisper: {s} decoder positions exceed "
                f"max_target_positions {self.config.max_target_positions}")
        hidden = self._embed(ids, jnp.arange(s))
        for layer in self.decoder_layers_list:
            hidden = layer(hidden, enc_hidden=enc_hidden)
        return self.decoder_ln(hidden)

    def decode_cached(self, ids, self_caches, cross_caches):
        s = ids.shape[1]
        if "lengths" in self_caches[0]:     # ragged serving rows
            positions = (self_caches[0]["lengths"][:, None]
                         + jnp.arange(s)[None, :])
        else:
            positions = self_caches[0]["pos"] + jnp.arange(s)
        hidden = self._embed(ids, positions)
        new_self, new_cross = [], []
        for layer, sc, cc in zip(self.decoder_layers_list, self_caches,
                                 cross_caches):
            hidden, sc, cc = layer(hidden, self_cache=sc, cross_cache=cc)
            new_self.append(sc)
            new_cross.append(cc)
        return self.decoder_ln(hidden), new_self, new_cross


class WhisperForConditionalGeneration(Layer):
    """Whisper ASR seq2seq LM — tied lm head (proj_out)."""

    def __init__(self, config: WhisperConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.model = WhisperModel(config)

    def lm_head_logits(self, hidden):
        from .llama import tied_lm_head_logits

        return tied_lm_head_logits(hidden, self.model.embed_tokens.weight)

    def forward(self, input_features, decoder_input_ids, labels=None):
        enc = self.model.encode(input_features)
        dec = self.model.decode(decoder_input_ids, enc)
        logits = self.lm_head_logits(dec)
        if labels is None:
            return logits
        from .llama import causal_lm_loss

        return causal_lm_loss(logits, labels), logits

    def _init_caches(self, enc, batch, max_len):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        h = cfg.decoder_attention_heads
        d = cfg.d_model // h
        self_caches, cross_caches = [], []
        for layer in self.model.decoder_layers_list:
            self_caches.append({
                "k": jnp.zeros((batch, max_len, h, d), dt),
                "v": jnp.zeros((batch, max_len, h, d), dt),
                "pos": jnp.asarray(0, jnp.int32)})
            ca = layer.encoder_attn
            cross_caches.append(
                {"k": unwrap(ca._split(ca.k_proj(enc), enc.shape[0])),
                 "v": unwrap(ca._split(ca.v_proj(enc), enc.shape[0]))})
        return self_caches, cross_caches

    def generate(self, input_features, decoder_input_ids=None,
                 max_new_tokens=20, do_sample=False, temperature=1.0,
                 top_k=0, top_p=1.0, eos_token_id=_UNSET, num_beams=1,
                 length_penalty=1.0, early_stopping=False, **unsupported):
        """Cached autoregressive transcription. ``decoder_input_ids``
        seeds the decoder (task/language prompt tokens); defaults to
        ``decoder_start_token_id``. Token suppression/forcing beyond the
        seed belongs to the tokenizer pipeline, not the model.
        ``num_beams>1``: HF-semantics beam search (greedy scoring)."""
        from ..generation import reject_non_default_kwargs

        reject_non_default_kwargs("Whisper", unsupported)
        from ..generation import reject_sampled_beams

        reject_sampled_beams("Whisper", num_beams, do_sample)
        from ..autograd import tape as _tape
        from ..framework import random as _random
        from ..generation import _select, encdec_beam_generate

        cfg = self.config
        eos = cfg.eos_token_id if eos_token_id is _UNSET else eos_token_id
        feats = (input_features if isinstance(input_features, Tensor)
                 else wrap(jnp.asarray(np.asarray(input_features))))
        B = feats.shape[0]
        if decoder_input_ids is None:
            seed = jnp.full((B, 1), cfg.decoder_start_token_id, jnp.int32)
        else:
            seed = jnp.asarray(
                unwrap(decoder_input_ids)
                if isinstance(decoder_input_ids, Tensor)
                else np.asarray(decoder_input_ids)).astype(jnp.int32)
        max_len = seed.shape[1] + max_new_tokens
        if max_len > cfg.max_target_positions:
            raise ValueError(
                f"seed ({seed.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_target_positions "
                f"{cfg.max_target_positions}")
        with _tape.no_grad():
            enc = self.model.encode(feats)
            self_c, cross_c = self._init_caches(enc, B, max_len)
            step = _get_whisper_decode_step(self, max_len)
            if num_beams > 1:
                return encdec_beam_generate(
                    self,
                    lambda m, t, s, c: m.model.decode_cached(t, s, c),
                    step, seed, self_c, cross_c, max_new_tokens,
                    num_beams, eos, length_penalty, early_stopping,
                    "_whisper_beam_steps")
            token = seed
            finished = jnp.zeros((B,), bool)
            out = []
            for _ in range(max_new_tokens):
                logits, self_c = step(token, self_c, cross_c)
                nxt = _select(logits[:, -1, :], _random.next_key(),
                              do_sample, float(temperature), int(top_k),
                              float(top_p))
                if eos is not None:
                    nxt = jnp.where(finished, eos, nxt)
                    finished = finished | (nxt == eos)
                token = nxt[:, None].astype(jnp.int32)
                out.append(token)
                if eos is not None and bool(finished.all()):
                    break
            return wrap(jnp.concatenate(out, axis=1))

    def num_parameters(self) -> int:
        return sum(p.size for p in self.parameters())


class _WhisperDecodeStep:
    def __init__(self, model, max_len):
        from ..autograd import tape as _tape
        from ..nn.layer import functional_weights

        def pure(state, token, self_caches, cross_caches):
            with functional_weights(model, state), _tape.no_grad():
                hidden, new_self, _ = model.model.decode_cached(
                    wrap(token), self_caches, cross_caches)
                logits = model.lm_head_logits(hidden)
            return unwrap(logits), [
                {k: (unwrap(v) if isinstance(v, Tensor) else v)
                 for k, v in c.items()} for c in new_self]

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, token, self_caches, cross_caches):
        return self._jitted(self._state, token, self_caches, cross_caches)


def _get_whisper_decode_step(model, max_len):
    from ..generation import _memoized_step

    return _memoized_step(model, "_whisper_decode_steps", (max_len,),
                          lambda: _WhisperDecodeStep(model, max_len))


# ---------------------------------------------------------------------------
# HuggingFace checkpoint interop
# ---------------------------------------------------------------------------

def whisper_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a WhisperForConditionalGeneration from a transformers
    Whisper model (or a raw state dict + config)."""
    from .llama import _hf_get, _hf_to_np

    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    get = _hf_get(hf_config)
    kw = dict(vocab_size=get("vocab_size"), d_model=get("d_model"),
              encoder_layers=get("encoder_layers"),
              decoder_layers=get("decoder_layers"),
              encoder_attention_heads=get("encoder_attention_heads"),
              decoder_attention_heads=get("decoder_attention_heads"),
              encoder_ffn_dim=get("encoder_ffn_dim"),
              decoder_ffn_dim=get("decoder_ffn_dim"),
              num_mel_bins=get("num_mel_bins", 80),
              max_source_positions=get("max_source_positions", 1500),
              max_target_positions=get("max_target_positions", 448),
              activation_function=get("activation_function", "gelu"),
              scale_embedding=bool(get("scale_embedding", False)),
              decoder_start_token_id=get("decoder_start_token_id"),
              eos_token_id=get("eos_token_id"),
              pad_token_id=get("pad_token_id"))
    if kw["activation_function"] != "gelu":
        raise NotImplementedError(
            f"whisper_from_hf: activation_function "
            f"{kw['activation_function']!r} not supported (gelu only)")
    kw.update(config_overrides)
    cfg = WhisperConfig(**kw)
    model = WhisperForConditionalGeneration(cfg)

    plan = {
        "model.conv1.weight": ("model.encoder.conv1.weight", False),
        "model.conv1.bias": ("model.encoder.conv1.bias", False),
        "model.conv2.weight": ("model.encoder.conv2.weight", False),
        "model.conv2.bias": ("model.encoder.conv2.bias", False),
        "model.encoder_pos.weight": (
            "model.encoder.embed_positions.weight", False),
        "model.embed_tokens.weight": (
            "model.decoder.embed_tokens.weight", False),
        "model.decoder_pos.weight": (
            "model.decoder.embed_positions.weight", False),
        "model.encoder_ln.weight": ("model.encoder.layer_norm.weight",
                                    False),
        "model.encoder_ln.bias": ("model.encoder.layer_norm.bias", False),
        "model.decoder_ln.weight": ("model.decoder.layer_norm.weight",
                                    False),
        "model.decoder_ln.bias": ("model.decoder.layer_norm.bias", False),
    }
    for side, n, ours_list in (("encoder", cfg.encoder_layers,
                                "encoder_layers_list"),
                               ("decoder", cfg.decoder_layers,
                                "decoder_layers_list")):
        for i in range(n):
            hf = f"model.{side}.layers.{i}"
            ours = f"model.{ours_list}.{i}"
            attns = [("self_attn", "self_attn")]
            if side == "decoder":
                attns.append(("encoder_attn", "encoder_attn"))
            for ours_attn, hf_attn in attns:
                for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
                    plan[f"{ours}.{ours_attn}.{proj}.weight"] = (
                        f"{hf}.{hf_attn}.{proj}.weight", True)
                    if proj != "k_proj":    # whisper: no k bias
                        plan[f"{ours}.{ours_attn}.{proj}.bias"] = (
                            f"{hf}.{hf_attn}.{proj}.bias", False)
                plan[f"{ours}.{ours_attn}_layer_norm.weight"] = (
                    f"{hf}.{hf_attn}_layer_norm.weight", False)
                plan[f"{ours}.{ours_attn}_layer_norm.bias"] = (
                    f"{hf}.{hf_attn}_layer_norm.bias", False)
            for fc in ("fc1", "fc2"):
                plan[f"{ours}.{fc}.weight"] = (f"{hf}.{fc}.weight", True)
                plan[f"{ours}.{fc}.bias"] = (f"{hf}.{fc}.bias", False)
            plan[f"{ours}.final_layer_norm.weight"] = (
                f"{hf}.final_layer_norm.weight", False)
            plan[f"{ours}.final_layer_norm.bias"] = (
                f"{hf}.final_layer_norm.bias", False)

    mapped, consumed = {}, set()
    for name, (hf_key, transpose) in plan.items():
        if hf_key not in state:
            raise KeyError(f"whisper_from_hf: checkpoint missing {hf_key!r}")
        v = _hf_to_np(state[hf_key])
        mapped[name] = v.T if transpose else v
        consumed.add(hf_key)
    leftovers = [k for k in state if k not in consumed
                 and k != "proj_out.weight"]   # tied-head alias
    if leftovers:
        raise ValueError(
            f"whisper_from_hf: checkpoint tensors this model cannot "
            f"represent: {leftovers[:5]}"
            f"{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected
    if missing:
        raise KeyError(f"whisper_from_hf: model keys not covered: "
                       f"{missing[:5]}")
    return model
