"""Stable-Diffusion-3 MMDiT + diffusion training objectives/samplers
(BASELINE.json config 4: "DiT / Stable-Diffusion-3 (PaddleMIX)").

The class-conditional DiT backbone lives in ``paddle_tpu.vision.models.dit``;
this module adds the pieces the SD3 recipe needs on top of it:

- **MMDiT** — the SD3 two-stream transformer (Esser et al.): text-context
  tokens and image-latent tokens each keep their own weights and adaLN
  modulation, attention runs ONCE over the concatenation of both streams,
  and the conditioning vector is timestep + pooled-text.
- **rectified_flow_loss** — the SD3 training objective (velocity matching on
  the linear noise path, logit-normal timestep density).
- **ddpm_eps_loss** — the classic DiT objective (eps-prediction, linear
  betas), usable with ``vision.models.dit.DiT`` directly.
- **sample_flow / sample_ddim** — Euler rectified-flow and DDIM samplers
  with classifier-free guidance; each whole sampling loop is ONE
  ``lax.scan`` (one device dispatch), TPU-native rather than a host loop.

Role anchors: the reference platform trains these models through PaddleMIX
ppdiffusers on top of the transformer stack
(python/paddle/nn/layer/transformer.py) and fused attention
(paddle/phi/kernels/fusion/); here the same workload rides paddle_tpu.nn
blocks, so dp/fsdp/tp sharding via ``distributed.engine.parallelize`` and
``jit.TrainStep`` work unchanged.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import nn
from ..framework import random as _random
from ..nn.layer import Layer
import contextlib

from ..tensor_class import unwrap, wrap
from ..vision.models.dit import (FinalLayer, TimestepEmbedder,
                                 _sincos_pos_embed)


@contextlib.contextmanager
def _eval_mode(model):
    """Run the sampler with the model in eval mode, restoring the caller's
    training flag after (train loops sample periodically; the sampler must
    not leave the model permanently in eval)."""
    was_training = model.training
    model.eval()
    try:
        yield
    finally:
        if was_training:
            model.train()


@dataclasses.dataclass
class MMDiTConfig:
    input_size: int = 32
    patch_size: int = 2
    in_channels: int = 16           # SD3 VAE has 16 latent channels
    hidden_size: int = 1536
    depth: int = 24
    num_heads: int = 24
    mlp_ratio: float = 4.0
    context_dim: int = 4096         # text-encoder token width
    pooled_dim: int = 2048          # pooled text vector width

    @staticmethod
    def tiny(**kw):
        base = dict(input_size=8, patch_size=2, in_channels=4,
                    hidden_size=64, depth=2, num_heads=4,
                    context_dim=32, pooled_dim=16)
        base.update(kw)
        return MMDiTConfig(**base)


class _PatchEmbed(Layer):
    """[B, C, H, W] -> [B, T, hidden] via reshape + ONE Linear — identical
    math to the strided conv patchify but a single large MXU matmul."""

    def __init__(self, patch_size, in_channels, hidden_size):
        super().__init__()
        self.patch_size = patch_size
        self.proj = nn.Linear(patch_size * patch_size * in_channels,
                              hidden_size)

    def forward(self, x):
        a = unwrap(x)
        b, c, h, w = a.shape
        p = self.patch_size
        a = a.reshape(b, c, h // p, p, w // p, p)
        a = a.transpose(0, 2, 4, 3, 5, 1).reshape(
            b, (h // p) * (w // p), p * p * c)
        return self.proj(wrap(a))


def _modulate(x, shift, scale):
    return x * (1.0 + scale[:, None, :]) + shift[:, None, :]


class MMDiTBlock(Layer):
    """Joint-attention block: each stream owns its norms/qkv/mlp/adaLN;
    attention runs once over [text ++ image] tokens, split back after.
    ``context_last`` marks the final block, where the text stream ends."""

    def __init__(self, hidden_size, num_heads, mlp_ratio=4.0,
                 context_last=False):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        self.context_last = context_last
        inner = int(hidden_size * mlp_ratio)

        def stream(pre_only=False):
            # pre_only (SD3 "context_pre_only"): the text stream of the final
            # block only feeds the joint attention — no proj/mlp/gates, and
            # just shift+scale from adaLN, so no dead weights ride the
            # optimizer
            s = Layer()
            s.norm1 = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                   weight_attr=False, bias_attr=False)
            s.qkv = nn.Linear(hidden_size, 3 * hidden_size)
            if not pre_only:
                s.proj = nn.Linear(hidden_size, hidden_size)
                s.norm2 = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                       weight_attr=False, bias_attr=False)
                s.fc1 = nn.Linear(hidden_size, inner)
                s.fc2 = nn.Linear(inner, hidden_size)
            s.adaLN = nn.Linear(hidden_size,
                                (2 if pre_only else 6) * hidden_size)
            s.adaLN.weight._array = jnp.zeros_like(s.adaLN.weight._array)
            s.adaLN.bias._array = jnp.zeros_like(s.adaLN.bias._array)
            return s

        self.img = stream()
        self.txt = stream(pre_only=context_last)

    def _qkv(self, s, x, shift, scale):
        h = _modulate(unwrap(s.norm1(wrap(x))), shift, scale)
        qkv = unwrap(s.qkv(wrap(h)))
        b, t, _ = qkv.shape
        qkv = qkv.reshape(b, t, 3, self.num_heads, self.head_dim)
        return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    @staticmethod
    def _mlp(s, x):
        return unwrap(s.fc2(nn.functional.gelu(s.fc1(wrap(x)),
                                               approximate=True)))

    def forward(self, img, txt, c):
        im, tx = unwrap(img), unwrap(txt)
        silu_c = nn.functional.silu(c)
        mi = jnp.split(unwrap(self.img.adaLN(silu_c)), 6, axis=-1)
        mt = jnp.split(unwrap(self.txt.adaLN(silu_c)),
                       2 if self.context_last else 6, axis=-1)
        qi, ki, vi = self._qkv(self.img, im, mi[0], mi[1])
        qt, kt, vt = self._qkv(self.txt, tx, mt[0], mt[1])
        tt = qt.shape[1]
        q = jnp.concatenate([qt, qi], axis=1)   # text first (SD3 layout)
        k = jnp.concatenate([kt, ki], axis=1)
        v = jnp.concatenate([vt, vi], axis=1)
        out = unwrap(nn.functional.scaled_dot_product_attention(
            wrap(q), wrap(k), wrap(v), is_causal=False))
        b, tot = out.shape[0], out.shape[1]
        out = out.reshape(b, tot, self.num_heads * self.head_dim)
        ot, oi = out[:, :tt], out[:, tt:]

        im = im + mi[2][:, None, :] * unwrap(self.img.proj(wrap(oi)))
        im = im + mi[5][:, None, :] * self._mlp(self.img, _modulate(
            unwrap(self.img.norm2(wrap(im))), mi[3], mi[4]))
        if self.context_last:
            return wrap(im), txt
        tx = tx + mt[2][:, None, :] * unwrap(self.txt.proj(wrap(ot)))
        tx = tx + mt[5][:, None, :] * self._mlp(self.txt, _modulate(
            unwrap(self.txt.norm2(wrap(tx))), mt[3], mt[4]))
        return wrap(im), wrap(tx)


class MMDiT(Layer):
    """SD3 rectified-flow transformer: forward(latents [B,C,H,W],
    t [B] in [0,1], context [B,L,context_dim], pooled [B,pooled_dim])
    -> velocity prediction [B,C,H,W]."""

    def __init__(self, config: MMDiTConfig):
        super().__init__()
        self.config = cfg = config
        self.grid = cfg.input_size // cfg.patch_size
        self.x_embed = _PatchEmbed(cfg.patch_size, cfg.in_channels,
                                   cfg.hidden_size)
        self.ctx_embed = nn.Linear(cfg.context_dim, cfg.hidden_size)
        self.t_embed = TimestepEmbedder(cfg.hidden_size)
        self.pool_fc1 = nn.Linear(cfg.pooled_dim, cfg.hidden_size)
        self.pool_fc2 = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.blocks = nn.LayerList([
            MMDiTBlock(cfg.hidden_size, cfg.num_heads, cfg.mlp_ratio,
                       context_last=(i == cfg.depth - 1))
            for i in range(cfg.depth)])
        self.final = FinalLayer(cfg.hidden_size, cfg.patch_size,
                                cfg.in_channels)
        self._pos = jnp.asarray(_sincos_pos_embed(cfg.hidden_size, self.grid))

    def forward(self, x, t, context, pooled):
        cfg = self.config
        # SD3 scales continuous t in [0,1] by 1000 for the sinusoid features
        timesteps = wrap(unwrap(t).astype(jnp.float32) * 1000.0)
        img = wrap(unwrap(self.x_embed(x)) + self._pos[None])
        txt = self.ctx_embed(context)
        c = self.t_embed(timesteps) + self.pool_fc2(
            nn.functional.silu(self.pool_fc1(pooled)))
        for blk in self.blocks:
            img, txt = blk(img, txt, c)
        out = unwrap(self.final(img, c))
        b = out.shape[0]
        p, g, ch = cfg.patch_size, self.grid, cfg.in_channels
        out = out.reshape(b, g, g, p, p, ch)
        out = jnp.einsum("bhwpqc->bchpwq", out)
        return wrap(out.reshape(b, ch, g * p, g * p))


# ---------------------------------------------------------------------------
# Training objectives (plain functions over the model — TrainStep /
# parallelize shard them like any loss)
# ---------------------------------------------------------------------------

def cfg_label_dropout(labels, num_classes, prob):
    """Replace labels with the null class (id == num_classes) with
    probability ``prob`` — train-time classifier-free-guidance dropout for
    ``vision.models.dit.LabelEmbedder``'s null slot."""
    y = unwrap(labels)
    drop = jax.random.bernoulli(_random.next_key(), prob, y.shape)
    return wrap(jnp.where(drop, num_classes, y).astype(y.dtype))


def rectified_flow_loss(model, x0, *cond, logit_normal=True):
    """SD3 objective: x_t = (1-t)·x0 + t·n, target velocity v = n − x0,
    t ~ logit-normal(0,1) (the SD3 timestep density) or uniform."""
    a = unwrap(x0)
    kt, kn = jax.random.split(_random.next_key())
    if logit_normal:
        t = jax.nn.sigmoid(jax.random.normal(kt, (a.shape[0],)))
    else:
        t = jax.random.uniform(kt, (a.shape[0],))
    n = jax.random.normal(kn, a.shape, jnp.float32).astype(a.dtype)
    tb = t.astype(a.dtype)[:, None, None, None]
    xt = (1.0 - tb) * a + tb * n
    v = unwrap(model(wrap(xt), wrap(t), *cond)).astype(jnp.float32)
    target = (n - a).astype(jnp.float32)
    return wrap(jnp.mean((v - target) ** 2))


def _linear_alphas_bar(num_train_steps):
    betas = jnp.linspace(1e-4, 0.02, num_train_steps, dtype=jnp.float32)
    return jnp.cumprod(1.0 - betas)


def _eps_of(model, x, tvec, *cond):
    """Noise prediction from a DiT-style model, dropping the sigma channels
    when the model predicts (eps, sigma)."""
    out = unwrap(model(wrap(x), wrap(tvec), *cond))
    c_in = x.shape[1]
    return out[:, :c_in].astype(jnp.float32)


def ddpm_eps_loss(model, x0, *cond, num_train_steps=1000):
    """Classic DiT objective: predict eps at a uniform integer timestep
    under the linear-beta schedule."""
    a = unwrap(x0)
    kt, kn = jax.random.split(_random.next_key())
    t = jax.random.randint(kt, (a.shape[0],), 0, num_train_steps)
    ab = _linear_alphas_bar(num_train_steps)[t].astype(a.dtype)[
        :, None, None, None]
    n = jax.random.normal(kn, a.shape, jnp.float32).astype(a.dtype)
    xt = jnp.sqrt(ab) * a + jnp.sqrt(1.0 - ab) * n
    e = _eps_of(model, xt, t, *cond)
    return wrap(jnp.mean((e - n.astype(jnp.float32)) ** 2))


# ---------------------------------------------------------------------------
# Samplers — each whole loop is ONE lax.scan
# ---------------------------------------------------------------------------

def sample_flow(model, shape, *cond, steps=28, guidance_scale=0.0,
                uncond=None, key=None):
    """Euler rectified-flow sampler t: 1 → 0 with optional CFG
    (``uncond`` = the unconditional cond-tuple: null labels / empty text)."""
    key = key if key is not None else _random.next_key()
    x1 = jax.random.normal(key, shape, jnp.float32)
    ts = jnp.linspace(1.0, 0.0, steps + 1)
    cond_a = [unwrap(c) for c in cond]
    unc_a = [unwrap(c) for c in uncond] if uncond is not None else None

    def vel(x, tvec):
        v = unwrap(model(wrap(x), wrap(tvec),
                         *[wrap(c) for c in cond_a])).astype(jnp.float32)
        if guidance_scale > 0.0 and unc_a is not None:
            vu = unwrap(model(wrap(x), wrap(tvec),
                              *[wrap(c) for c in unc_a])).astype(jnp.float32)
            v = vu + guidance_scale * (v - vu)
        return v

    def body(x, i):
        t0, t1 = ts[i], ts[i + 1]
        tvec = jnp.full((shape[0],), t0, jnp.float32)
        return x + (t1 - t0) * vel(x, tvec), None

    with _eval_mode(model):
        out, _ = jax.lax.scan(body, x1, jnp.arange(steps))
    return wrap(out)


def sample_ddim(model, shape, *cond, steps=50, num_train_steps=1000,
                guidance_scale=0.0, uncond=None, key=None):
    """Deterministic DDIM over the linear-beta schedule; works with
    ``vision.models.dit.DiT`` (sigma channels dropped)."""
    key = key if key is not None else _random.next_key()
    x = jax.random.normal(key, shape, jnp.float32)
    ab_all = _linear_alphas_bar(num_train_steps)
    idx = jnp.linspace(num_train_steps - 1, 0, steps).astype(jnp.int32)
    cond_a = [unwrap(c) for c in cond]
    unc_a = [unwrap(c) for c in uncond] if uncond is not None else None

    def eps(x, tvec):
        e = _eps_of(model, x, tvec, *[wrap(c) for c in cond_a])
        if guidance_scale > 0.0 and unc_a is not None:
            eu = _eps_of(model, x, tvec, *[wrap(c) for c in unc_a])
            e = eu + guidance_scale * (e - eu)
        return e

    def body(x, i):
        t = idx[i]
        ab_t = ab_all[t]
        # alpha_bar of the next (smaller) timestep; 1.0 at the final step
        ab_p = jnp.where(i + 1 < steps,
                         ab_all[idx[jnp.minimum(i + 1, steps - 1)]], 1.0)
        tvec = jnp.full((shape[0],), t, jnp.int32)
        e = eps(x, tvec)
        x0 = (x - jnp.sqrt(1.0 - ab_t) * e) / jnp.sqrt(ab_t)
        return jnp.sqrt(ab_p) * x0 + jnp.sqrt(1.0 - ab_p) * e, None

    with _eval_mode(model):
        out, _ = jax.lax.scan(body, x, jnp.arange(steps))
    return wrap(out)
