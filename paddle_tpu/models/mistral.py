"""Mistral decoder family (sliding-window attention).

Role parity: PaddleNLP's mistral modeling in the reference ecosystem — the
Llama decoder recipe with causal sliding-window attention (window 4096 in
v0.1/v0.2). Expressed as a LlamaConfig specialization: the splash kernel
skips KV blocks outside the band (O(seq*window) attention work), and all
training / hybrid-parallel / serving paths are the shared Llama machinery.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from .llama import LlamaConfig, LlamaForCausalLM, _from_hf


@dataclasses.dataclass
class MistralConfig(LlamaConfig):
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 32768
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    sliding_window: Optional[int] = 4096  # the Mistral signature deviation

    @staticmethod
    def mistral_7b(**kw):
        return MistralConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    sliding_window=32, dtype="float32")
        base.update(kw)
        return MistralConfig(**base)


class MistralForCausalLM(LlamaForCausalLM):
    """Mistral causal LM — Llama decoder with sliding-window attention."""


def mistral_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a MistralForCausalLM from a transformers Mistral model (or a
    raw state dict + config)."""
    return _from_hf(MistralConfig, MistralForCausalLM, hf_model_or_state,
                    hf_config, **config_overrides)
