"""Gemma2 decoder family (2B / 9B / 27B).

Gemma's three knobs (GeGLU, (1+w) norms, scaled embeddings — gemma.py)
plus the four Gemma2 deviations, each expressed at the trunk level so the
cached/serving machinery still applies:

- sandwich norms: post-attention norm on the attention OUTPUT before the
  residual add, and a pre/post pair around the MLP (own decoder layer via
  the ``_make_decoder_layer`` hook);
- ``query_pre_attn_scalar``: softmax scale folded into q after projection
  (LlamaAttention.q_premul — exact on every path since RoPE is linear);
- tanh logit soft caps: ``attn_logit_softcapping`` on attention scores
  (the flash kernel falls back to the dense path; paged decode rides the
  exact gather reference, so the continuous-batching engine serves
  softcapped models; CP refuses loudly) and ``final_logit_softcapping``
  applied in the base lm_head_logits (training loss, generate, beam,
  speculative, serving);
- alternating sliding/full attention via the trunk ``layer_types``
  schedule.

``gemma2_from_hf`` converts transformers checkpoints (Llama key layout +
the two extra per-layer norms).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from ..nn.layer import Layer
from .gemma import GemmaConfig
from .llama import (LlamaAttention, LlamaForCausalLM, LlamaMLP, LlamaModel,
                    LlamaRMSNorm, _from_hf, _hf_get, layer_window)


@dataclasses.dataclass
class Gemma2Config(GemmaConfig):
    # Gemma2-9B shape
    vocab_size: int = 256000
    hidden_size: int = 3584
    intermediate_size: int = 14336
    num_hidden_layers: int = 42
    num_attention_heads: int = 16
    num_key_value_heads: int = 8
    head_dim: Optional[int] = 256
    query_pre_attn_scalar: Optional[float] = 256.0
    attn_logit_softcapping: Optional[float] = 50.0
    final_logit_softcapping: Optional[float] = 30.0
    sliding_window: Optional[int] = 4096

    def __post_init__(self):
        if self.layer_types is None and self.sliding_window is not None:
            # the Gemma2 alternation: even layers sliding, odd layers full
            self.layer_types = tuple(
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(self.num_hidden_layers))
        super().__post_init__()

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, head_dim=32,
                    query_pre_attn_scalar=64.0, sliding_window=16,
                    max_position_embeddings=256, dtype="float32")
        base.update(kw)
        return Gemma2Config(**base)


class Gemma2DecoderLayer(Layer):
    """Sandwich-norm decoder block: norm(attn) before the residual add and
    a pre/post norm pair around the MLP (four (1+w) RMSNorms per layer)."""

    def __init__(self, config: Gemma2Config):
        super().__init__(dtype=config.dtype)
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)
        self.pre_feedforward_layernorm = LlamaRMSNorm(config)
        self.post_feedforward_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden_states, cos, sin, attention_mask=None,
                kv_cache=None):
        residual = hidden_states
        hidden_states = self.input_layernorm(hidden_states)
        if kv_cache is not None:
            hidden_states, kv_cache = self.self_attn(
                hidden_states, cos, sin, attention_mask, kv_cache)
        else:
            hidden_states = self.self_attn(hidden_states, cos, sin,
                                           attention_mask)
        hidden_states = self.post_attention_layernorm(hidden_states)
        hidden_states = residual + hidden_states

        residual = hidden_states
        hidden_states = self.pre_feedforward_layernorm(hidden_states)
        hidden_states = self.mlp(hidden_states)
        hidden_states = self.post_feedforward_layernorm(hidden_states)
        hidden_states = residual + hidden_states
        if kv_cache is not None:
            return hidden_states, kv_cache
        return hidden_states


class Gemma2Model(LlamaModel):
    @staticmethod
    def _make_decoder_layer(config, layer_idx):
        layer = Gemma2DecoderLayer(config)
        layer.self_attn.window = layer_window(config, layer_idx)
        return layer


class Gemma2ForCausalLM(LlamaForCausalLM):
    """Gemma2 causal LM — sandwich-norm trunk; the final-logit soft cap
    is a base-trunk behavior (LlamaForCausalLM.lm_head_logits applies
    ``final_logit_softcapping`` for every family)."""

    model_cls = Gemma2Model

    def __init__(self, config: Gemma2Config):
        if config.hidden_act != "gelu_pytorch_tanh":
            raise ValueError("Gemma2 uses hidden_act='gelu_pytorch_tanh'")
        if not (config.rms_norm_offset and config.scale_embeddings):
            raise ValueError("Gemma2 needs rms_norm_offset=True and "
                             "scale_embeddings=True (the Gemma base knobs)")
        if not config.tie_word_embeddings:
            raise ValueError("Gemma2 ties the lm head to the embedding")
        super().__init__(config)


def gemma2_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a Gemma2ForCausalLM from a transformers Gemma2 model (or a
    raw state dict + config)."""
    src = hf_config if hf_config is not None else hf_model_or_state.config
    get = _hf_get(src)
    config_overrides.setdefault(
        "hidden_act", get("hidden_activation") or "gelu_pytorch_tanh")
    config_overrides.setdefault("rms_norm_offset", True)
    config_overrides.setdefault("scale_embeddings", True)
    config_overrides.setdefault("query_pre_attn_scalar",
                                get("query_pre_attn_scalar"))
    config_overrides.setdefault("attn_logit_softcapping",
                                get("attn_logit_softcapping"))
    config_overrides.setdefault("final_logit_softcapping",
                                get("final_logit_softcapping"))
    # the base mapper's window logic is mistral-keyed; Gemma2's schedule
    # arrives as the trunk layer_types + uniform window
    config_overrides.setdefault("sliding_window", get("sliding_window"))
    lt = get("layer_types")
    config_overrides.setdefault("layer_types",
                                tuple(lt) if lt is not None else None)
    return _from_hf(Gemma2Config, Gemma2ForCausalLM, hf_model_or_state,
                    hf_config,
                    extra_layer_norms=("pre_feedforward_layernorm",
                                       "post_feedforward_layernorm"),
                    **config_overrides)
