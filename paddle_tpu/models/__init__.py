"""Model zoo: flagship pretraining models (SURVEY §6 / BASELINE.json
workload configs): Llama-3 (+ Qwen2 bias / Mistral sliding-window
variants), GPT-2 (learned positions), DeepSeekMoE/Qwen2-MoE,
DeepSeek-V2/V3 (MLA: compressed-latent KV cache + group-limited
routing), ERNIE (encoder) + ERNIE-4.5 (MoE decoder), T5 and BART
encoder-decoders, SD3 MMDiT (DiT backbone + AutoencoderKL live in
vision.models). Every family has HF checkpoint interop with parity
tests."""
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaDecoderLayer, LlamaForCausalLMPipe)

_LAZY = {
    "llama_moe": ("llama_moe", None),
    "LlamaMoEConfig": ("llama_moe", "LlamaMoEConfig"),
    "LlamaMoEForCausalLM": ("llama_moe", "LlamaMoEForCausalLM"),
    "deepseek": ("deepseek", None),
    "DeepseekV2Config": ("deepseek", "DeepseekV2Config"),
    "DeepseekV2ForCausalLM": ("deepseek", "DeepseekV2ForCausalLM"),
    "DeepseekForCausalLMPipe": ("deepseek", "DeepseekForCausalLMPipe"),
    "deepseek_from_hf": ("deepseek", "deepseek_from_hf"),
    "ernie": ("ernie", None),
    "ErnieConfig": ("ernie", "ErnieConfig"),
    "ErnieModel": ("ernie", "ErnieModel"),
    "ErnieForMaskedLM": ("ernie", "ErnieForMaskedLM"),
    "ErnieForSequenceClassification": ("ernie", "ErnieForSequenceClassification"),
    "ErnieForPretraining": ("ernie", "ErnieForPretraining"),
    "ernie45": ("ernie45", None),
    "Ernie45Config": ("ernie45", "Ernie45Config"),
    "Ernie45ForCausalLM": ("ernie45", "Ernie45ForCausalLM"),
    "ernie45_from_hf": ("ernie45", "ernie45_from_hf"),
    "sd3": ("sd3", None),
    "MMDiTConfig": ("sd3", "MMDiTConfig"),
    "MMDiT": ("sd3", "MMDiT"),
    "qwen2": ("qwen2", None),
    "Qwen2Config": ("qwen2", "Qwen2Config"),
    "Qwen2ForCausalLM": ("qwen2", "Qwen2ForCausalLM"),
    "qwen2_from_hf": ("qwen2", "qwen2_from_hf"),
    "qwen3": ("qwen3", None),
    "Qwen3Config": ("qwen3", "Qwen3Config"),
    "Qwen3ForCausalLM": ("qwen3", "Qwen3ForCausalLM"),
    "qwen3_from_hf": ("qwen3", "qwen3_from_hf"),
    "glm": ("glm", None),
    "GlmConfig": ("glm", "GlmConfig"),
    "GlmForCausalLM": ("glm", "GlmForCausalLM"),
    "Glm4Config": ("glm", "Glm4Config"),
    "Glm4ForCausalLM": ("glm", "Glm4ForCausalLM"),
    "glm_from_hf": ("glm", "glm_from_hf"),
    "glm4_from_hf": ("glm", "glm4_from_hf"),
    "gemma": ("gemma", None),
    "GemmaConfig": ("gemma", "GemmaConfig"),
    "GemmaForCausalLM": ("gemma", "GemmaForCausalLM"),
    "gemma_from_hf": ("gemma", "gemma_from_hf"),
    "gemma2": ("gemma2", None),
    "Gemma2Config": ("gemma2", "Gemma2Config"),
    "Gemma2ForCausalLM": ("gemma2", "Gemma2ForCausalLM"),
    "gemma2_from_hf": ("gemma2", "gemma2_from_hf"),
    "whisper": ("whisper", None),
    "WhisperConfig": ("whisper", "WhisperConfig"),
    "WhisperForConditionalGeneration": ("whisper", "WhisperForConditionalGeneration"),
    "whisper_from_hf": ("whisper", "whisper_from_hf"),
    "llava": ("llava", None),
    "LlavaConfig": ("llava", "LlavaConfig"),
    "LlavaForConditionalGeneration": ("llava", "LlavaForConditionalGeneration"),
    "CLIPVisionConfig": ("llava", "CLIPVisionConfig"),
    "CLIPVisionTower": ("llava", "CLIPVisionTower"),
    "llava_from_hf": ("llava", "llava_from_hf"),
    "mixtral": ("mixtral", None),
    "MixtralConfig": ("mixtral", "MixtralConfig"),
    "MixtralForCausalLM": ("mixtral", "MixtralForCausalLM"),
    "mixtral_from_hf": ("mixtral", "mixtral_from_hf"),
    "olmo2": ("olmo2", None),
    "Olmo2Config": ("olmo2", "Olmo2Config"),
    "Olmo2ForCausalLM": ("olmo2", "Olmo2ForCausalLM"),
    "olmo2_from_hf": ("olmo2", "olmo2_from_hf"),
    "phi3": ("phi3", None),
    "Phi3Config": ("phi3", "Phi3Config"),
    "Phi3ForCausalLM": ("phi3", "Phi3ForCausalLM"),
    "phi3_from_hf": ("phi3", "phi3_from_hf"),
    "qwen2_moe": ("qwen2_moe", None),
    "Qwen2MoeConfig": ("qwen2_moe", "Qwen2MoeConfig"),
    "Qwen2MoeForCausalLM": ("qwen2_moe", "Qwen2MoeForCausalLM"),
    "qwen2_moe_from_hf": ("qwen2_moe", "qwen2_moe_from_hf"),
    "qwen3_moe": ("qwen3_moe", None),
    "Qwen3MoeConfig": ("qwen3_moe", "Qwen3MoeConfig"),
    "Qwen3MoeForCausalLM": ("qwen3_moe", "Qwen3MoeForCausalLM"),
    "qwen3_moe_from_hf": ("qwen3_moe", "qwen3_moe_from_hf"),
    "mistral": ("mistral", None),
    "MistralConfig": ("mistral", "MistralConfig"),
    "MistralForCausalLM": ("mistral", "MistralForCausalLM"),
    "mistral_from_hf": ("mistral", "mistral_from_hf"),
    "gpt2": ("gpt2", None),
    "GPT2Config": ("gpt2", "GPT2Config"),
    "GPT2LMHeadModel": ("gpt2", "GPT2LMHeadModel"),
    "gpt2_from_hf": ("gpt2", "gpt2_from_hf"),
    "t5": ("t5", None),
    "T5Config": ("t5", "T5Config"),
    "T5ForConditionalGeneration": ("t5", "T5ForConditionalGeneration"),
    "t5_from_hf": ("t5", "t5_from_hf"),
    "bart": ("bart", None),
    "BartConfig": ("bart", "BartConfig"),
    "BartForConditionalGeneration": ("bart", "BartForConditionalGeneration"),
    "bart_from_hf": ("bart", "bart_from_hf"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod_name, attr = _LAZY[name]
        mod = importlib.import_module(f".{mod_name}", __name__)
        globals()[mod_name] = mod
        return mod if attr is None else getattr(mod, attr)
    raise AttributeError(f"module 'paddle_tpu.models' has no attribute {name!r}")
