"""Model zoo: flagship pretraining models (SURVEY §6 workload configs)."""
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel, LlamaDecoderLayer


def __getattr__(name):
    if name in ("gpt", "GPTConfig", "GPTForCausalLM"):
        from . import gpt

        globals()["gpt"] = gpt
        if name != "gpt":
            return getattr(gpt, name)
        return gpt
    if name in ("moe", "MoEConfig", "LlamaMoEForCausalLM"):
        from . import moe as moe_mod

        globals()["moe"] = moe_mod
        if name != "moe":
            return getattr(moe_mod, name)
        return moe_mod
    raise AttributeError(f"module 'paddle_tpu.models' has no attribute {name!r}")
