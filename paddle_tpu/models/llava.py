"""LLaVA vision-language family (CLIP ViT tower + projector + Llama).

First multimodal member of the zoo. Architecture (HF modeling_llava):

- a CLIP vision transformer (conv patch embed + CLS + learned positions,
  pre-LN encoder blocks with quick-gelu MLPs) run to a chosen hidden
  layer (``vision_feature_layer``, default -2 — the PENULTIMATE block's
  output, no final post-LN), CLS dropped under the "default" strategy;
- a 2-linear gelu projector into the text embedding space;
- a Llama trunk consuming MERGED embeddings: every ``image_token_index``
  placeholder in the prompt is replaced by one projected patch feature
  (the trunk's ``inputs_embeds`` path). Decode after the multimodal
  prefill is the ordinary cached token path, so eos/sampling behave
  exactly like the text families.

``llava_from_hf`` converts a transformers ``LlavaForConditionalGeneration``
checkpoint (vision tower + projector mapped here; the language model
rides ``load_hf_llama`` on the re-prefixed subset).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn.layer import Layer
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap
from .llama import (LlamaConfig, LlamaForCausalLM, _hf_get, _hf_to_np,
                    hf_config_to_llama, load_hf_llama)


@dataclasses.dataclass
class CLIPVisionConfig:
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    image_size: int = 336
    patch_size: int = 14
    num_channels: int = 3
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @staticmethod
    def tiny(**kw):
        base = dict(hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    image_size=16, patch_size=8)
        base.update(kw)
        return CLIPVisionConfig(**base)


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


class CLIPAttention(Layer):
    """Bidirectional MHA with q/k/v/out biases (the CLIP block)."""

    def __init__(self, config: CLIPVisionConfig):
        super().__init__(dtype=config.dtype)
        d = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.q_proj = nn.Linear(d, d)
        self.k_proj = nn.Linear(d, d)
        self.v_proj = nn.Linear(d, d)
        self.out_proj = nn.Linear(d, d)

    def forward(self, x):
        from ..nn.functional.attention import _sdpa_ref

        b, s, d = x.shape
        h = self.num_heads
        q = self.q_proj(x).reshape([b, s, h, d // h])
        k = self.k_proj(x).reshape([b, s, h, d // h])
        v = self.v_proj(x).reshape([b, s, h, d // h])
        out = apply("clip_attention",
                    lambda q, k, v: _sdpa_ref(q, k, v, causal=False),
                    q, k, v)
        return self.out_proj(out.reshape([b, s, d]))


class CLIPEncoderLayer(Layer):
    def __init__(self, config: CLIPVisionConfig):
        super().__init__(dtype=config.dtype)
        d, eps = config.hidden_size, config.layer_norm_eps
        self.layer_norm1 = nn.LayerNorm(d, epsilon=eps)
        self.self_attn = CLIPAttention(config)
        self.layer_norm2 = nn.LayerNorm(d, epsilon=eps)
        self.fc1 = nn.Linear(d, config.intermediate_size)
        self.fc2 = nn.Linear(config.intermediate_size, d)

    def forward(self, x):
        x = x + self.self_attn(self.layer_norm1(x))
        h = self.fc1(self.layer_norm2(x))
        h = apply("quick_gelu", quick_gelu, h)
        return x + self.fc2(h)


class CLIPVisionTower(Layer):
    """CLIP ViT up to (and including) every encoder block — ``forward``
    returns the list of per-block hidden states so the caller can select
    ``vision_feature_layer`` (HF keeps them all too)."""

    def __init__(self, config: CLIPVisionConfig):
        super().__init__(dtype=config.dtype)
        d = config.hidden_size
        self.config = config
        self.patch_embedding = nn.Conv2D(
            config.num_channels, d, kernel_size=config.patch_size,
            stride=config.patch_size, bias_attr=False)
        self.class_embedding = self.create_parameter(
            [d], default_initializer=nn.initializer.Normal(0.0, 0.02))
        self.position_embedding = nn.Embedding(config.num_patches + 1, d)
        self.pre_layrnorm = nn.LayerNorm(d, epsilon=config.layer_norm_eps)
        self.layers = nn.LayerList(
            [CLIPEncoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.post_layernorm = nn.LayerNorm(d, epsilon=config.layer_norm_eps)

    def forward(self, pixel_values):
        """pixel_values [B, C, H, W] -> [embeddings, block1, ..., blockN]
        hidden states (each [B, 1 + num_patches, D])."""
        b = pixel_values.shape[0]
        patches = self.patch_embedding(pixel_values)       # [B, D, h, w]
        d = patches.shape[1]
        patches = patches.reshape([b, d, -1]).transpose([0, 2, 1])
        cls = self.class_embedding.reshape([1, 1, d]).expand([b, 1, d])
        from ..ops import manipulation as _manip

        x = _manip.concat([cls, patches], axis=1)
        pos = wrap(jnp.arange(x.shape[1], dtype=jnp.int32))
        x = x + self.position_embedding(pos)
        x = self.pre_layrnorm(x)
        states = [x]
        for layer in self.layers:
            x = layer(x)
            states.append(x)
        return states


class LlavaMultiModalProjector(Layer):
    def __init__(self, vision_hidden: int, text_hidden: int,
                 act: str = "gelu", dtype: str = "float32"):
        super().__init__(dtype=dtype)
        if act != "gelu":
            raise NotImplementedError(
                f"projector_hidden_act {act!r} not supported (gelu only)")
        self.linear_1 = nn.Linear(vision_hidden, text_hidden)
        self.linear_2 = nn.Linear(text_hidden, text_hidden)

    def forward(self, x):
        h = self.linear_1(x)
        h = apply("gelu", lambda a: jax.nn.gelu(a, approximate=False), h)
        return self.linear_2(h)


@dataclasses.dataclass
class LlavaConfig:
    text_config: LlamaConfig = None
    vision_config: CLIPVisionConfig = None
    image_token_index: int = 32000
    vision_feature_layer: int = -2
    vision_feature_select_strategy: str = "default"
    projector_hidden_act: str = "gelu"

    def __post_init__(self):
        if self.text_config is None:
            self.text_config = LlamaConfig()
        if self.vision_config is None:
            self.vision_config = CLIPVisionConfig()
        if self.vision_feature_select_strategy not in ("default", "full"):
            raise ValueError(
                "vision_feature_select_strategy must be 'default' or "
                f"'full', got {self.vision_feature_select_strategy!r}")

    @staticmethod
    def tiny(**kw):
        base = dict(
            text_config=LlamaConfig.tiny(num_hidden_layers=2),
            vision_config=CLIPVisionConfig.tiny(),
            image_token_index=511)
        base.update(kw)
        return LlavaConfig(**base)


class LlavaForConditionalGeneration(LlamaForCausalLM):
    """LLaVA: CLIP tower + projector + the Llama trunk.

    ``self.config`` is the TEXT config (the cache/generation machinery
    reads it); the multimodal wiring lives in ``self.llava_config``."""

    def __init__(self, config: LlavaConfig):
        super().__init__(config.text_config)
        self.llava_config = config
        self.vision_tower = CLIPVisionTower(config.vision_config)
        self.multi_modal_projector = LlavaMultiModalProjector(
            config.vision_config.hidden_size,
            config.text_config.hidden_size,
            act=config.projector_hidden_act,
            dtype=config.text_config.dtype)

    # ---- vision ------------------------------------------------------
    def get_image_features(self, pixel_values):
        """[n_images, C, H, W] -> [n_images, n_feats, text_hidden]."""
        states = self.vision_tower(pixel_values)
        feats = states[self.llava_config.vision_feature_layer]
        if self.llava_config.vision_feature_select_strategy == "default":
            feats = feats[:, 1:]                     # drop CLS
        return self.multi_modal_projector(feats)

    @property
    def multimodal_token_index(self) -> int:
        """The placeholder token id — with merge_multimodal and
        features_per_image, the serving engine's multimodal contract."""
        return self.llava_config.image_token_index

    def features_per_image(self) -> int:
        """Patch features each image contributes after the select
        strategy (the "default" strategy drops CLS)."""
        n = self.llava_config.vision_config.num_patches
        if self.llava_config.vision_feature_select_strategy == "full":
            n += 1
        return n

    def merge_multimodal(self, input_ids, pixel_values, n_feats=None):
        """Token embeddings with every image placeholder replaced by one
        projected patch feature, in order. Every tensor op here is
        tape-recorded (``apply``/Layer calls), so the vision tower and
        projector receive gradients in multimodal training.

        Eager calls validate the placeholder count against the images and
        locate positions on host; a TRACED caller (the serving engine's
        jitted merge step) passes the pre-validated ``n_feats`` so the
        positions come from a size-bounded ``jnp.nonzero`` instead."""
        from .llama import _scale_embed

        embeds = self.llama.embed_tokens(input_ids)
        embeds = _scale_embed(embeds.astype(self.config.dtype),
                              self.config)
        if pixel_values is None:
            return embeds
        feats = self.get_image_features(pixel_values)
        feats = feats.reshape([-1, feats.shape[-1]])
        if n_feats is None:
            ids_np = np.asarray(unwrap(input_ids))
            n_slots = int(
                (ids_np == self.llava_config.image_token_index).sum())
            if n_slots != feats.shape[0]:
                raise ValueError(
                    f"prompt has {n_slots} image tokens but the images "
                    f"produce {feats.shape[0]} features")
            n_feats = n_slots
        tok = self.llava_config.image_token_index

        def scatter(ids_arr, e, f):
            b_idx, s_idx = jnp.nonzero(ids_arr == tok, size=n_feats)
            return e.at[b_idx, s_idx].set(f.astype(e.dtype))

        return apply("multimodal_merge", scatter, input_ids, embeds, feats)

    # ---- text --------------------------------------------------------
    def forward(self, input_ids, pixel_values=None, labels=None,
                attention_mask=None):
        embeds = self.merge_multimodal(input_ids, pixel_values)
        hidden = self.llama(input_ids, attention_mask,
                            inputs_embeds=embeds)
        logits = self.lm_head_logits(hidden)
        if labels is None:
            return logits
        from .llama import causal_lm_loss

        return causal_lm_loss(logits, labels), logits

    def generate(self, input_ids, pixel_values=None, max_new_tokens=20,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 eos_token_id=None, **unsupported):
        """Multimodal generate: merged-embedding cached prefill, then the
        ordinary token decode loop. Text-only calls (no pixel_values)
        defer to the full-featured base generate()."""
        if pixel_values is None:
            return super().generate(
                input_ids, max_new_tokens=max_new_tokens,
                do_sample=do_sample, temperature=temperature, top_k=top_k,
                top_p=top_p, eos_token_id=eos_token_id, **unsupported)
        for k, v in unsupported.items():
            if v not in (None, False, 0, 1, 1.0, True):
                raise NotImplementedError(
                    f"llava generate with pixel_values: {k}={v!r} is not "
                    "supported")
        from ..framework import random as _random
        from ..generation import _empty_caches, sample_logits_rows

        ids = input_ids if isinstance(input_ids, Tensor) else wrap(
            jnp.asarray(np.asarray(input_ids)))
        B, S0 = ids.shape
        if max_new_tokens <= 0:
            return wrap(jnp.zeros((B, 0), jnp.int32))
        max_len = S0 + max_new_tokens
        embeds = self.merge_multimodal(ids, pixel_values)
        caches = _empty_caches(self, B, max_len)
        hidden, caches = self.llama.forward_cached(
            ids, caches, rope_len=max_len, inputs_embeds=embeds)
        # slice the last position BEFORE the lm head: the vocab matmul
        # runs on [B, 1, H], not the whole prompt
        last = unwrap(self.lm_head_logits(hidden[:, -1:]))[:, -1, :]
        out = []
        finished = np.zeros((B,), bool)
        for _ in range(max_new_tokens):
            if do_sample:
                nxt = sample_logits_rows(
                    jnp.asarray(last), _random.next_key(),
                    jnp.full((B,), True),
                    jnp.full((B,), float(temperature), jnp.float32),
                    jnp.full((B,), int(top_k), jnp.int32),
                    jnp.full((B,), float(top_p), jnp.float32))
            else:
                nxt = jnp.argmax(jnp.asarray(last), axis=-1)
            tok = np.asarray(nxt, np.int64)
            if eos_token_id is not None:
                tok = np.where(finished, eos_token_id, tok)
                finished |= tok == eos_token_id
            out.append(tok)
            if eos_token_id is not None and finished.all():
                break
            hidden, caches = self.llama.forward_cached(
                wrap(jnp.asarray(tok[:, None], jnp.int32)), caches,
                rope_len=max_len)
            last = unwrap(self.lm_head_logits(hidden))[:, -1, :]
        return wrap(jnp.asarray(np.stack(out, axis=1)))


# ---- HF interop ------------------------------------------------------------

def _hf_config_to_llava(hf_config, **overrides) -> LlavaConfig:
    get = _hf_get(hf_config)
    vc = get("vision_config")
    vget = _hf_get(vc if isinstance(vc, dict) else vc.to_dict()
                   if hasattr(vc, "to_dict") else vc)
    if vget("hidden_act", "quick_gelu") != "quick_gelu":
        raise NotImplementedError(
            "CLIP tower supports hidden_act='quick_gelu' only")
    vision = CLIPVisionConfig(
        hidden_size=vget("hidden_size"),
        intermediate_size=vget("intermediate_size"),
        num_hidden_layers=vget("num_hidden_layers"),
        num_attention_heads=vget("num_attention_heads"),
        image_size=vget("image_size"),
        patch_size=vget("patch_size"),
        num_channels=vget("num_channels", 3),
        layer_norm_eps=vget("layer_norm_eps", 1e-5))
    tc = get("text_config")
    text_overrides = overrides.pop("text_overrides", {})
    text = hf_config_to_llama(
        tc if isinstance(tc, dict) else tc, **text_overrides)
    kw = dict(
        text_config=text, vision_config=vision,
        image_token_index=get("image_token_index", 32000),
        vision_feature_layer=get("vision_feature_layer", -2),
        vision_feature_select_strategy=get(
            "vision_feature_select_strategy", "default"),
        projector_hidden_act=get("projector_hidden_act", "gelu"))
    kw.update(overrides)
    return LlavaConfig(**kw)


def load_hf_llava(model: LlavaForConditionalGeneration,
                  hf_state_dict) -> LlavaForConditionalGeneration:
    """Load a transformers Llava state dict: the language model through
    load_hf_llama on the re-prefixed subset; vision tower + projector
    mapped here (torch Linear [out,in] transposes; conv stays)."""
    lang, rest = {}, {}
    for k, v in hf_state_dict.items():
        for pre in ("model.language_model.", "language_model.model."):
            if k.startswith(pre):
                lang["model." + k[len(pre):]] = v
                break
        else:
            if k in ("lm_head.weight", "language_model.lm_head.weight"):
                lang["lm_head.weight"] = v
            else:
                rest[k] = v
    load_hf_llama(model, lang,
                  ignore_missing_prefixes=("vision_tower",
                                           "multi_modal_projector"))

    mapped, consumed = {}, set()

    def take(hf_key, transpose):
        for pre in ("model.", ""):
            if pre + hf_key in rest:
                consumed.add(pre + hf_key)
                v = _hf_to_np(rest[pre + hf_key])
                return v.T if transpose else v
        raise KeyError(f"load_hf_llava: missing {hf_key!r}")

    vt, hf_vt = "vision_tower", "vision_tower.vision_model"
    mapped[f"{vt}.patch_embedding.weight"] = take(
        f"{hf_vt}.embeddings.patch_embedding.weight", False)
    mapped[f"{vt}.class_embedding"] = take(
        f"{hf_vt}.embeddings.class_embedding", False)
    mapped[f"{vt}.position_embedding.weight"] = take(
        f"{hf_vt}.embeddings.position_embedding.weight", False)
    for norm in ("pre_layrnorm", "post_layernorm"):
        for p in ("weight", "bias"):
            mapped[f"{vt}.{norm}.{p}"] = take(f"{hf_vt}.{norm}.{p}", False)
    L = model.llava_config.vision_config.num_hidden_layers
    for i in range(L):
        ours, hf = f"{vt}.layers.{i}", f"{hf_vt}.encoder.layers.{i}"
        for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
            mapped[f"{ours}.self_attn.{proj}.weight"] = take(
                f"{hf}.self_attn.{proj}.weight", True)
            mapped[f"{ours}.self_attn.{proj}.bias"] = take(
                f"{hf}.self_attn.{proj}.bias", False)
        for norm in ("layer_norm1", "layer_norm2"):
            for p in ("weight", "bias"):
                mapped[f"{ours}.{norm}.{p}"] = take(f"{hf}.{norm}.{p}",
                                                    False)
        for fc, hf_fc in (("fc1", "mlp.fc1"), ("fc2", "mlp.fc2")):
            mapped[f"{ours}.{fc}.weight"] = take(f"{hf}.{hf_fc}.weight",
                                                 True)
            mapped[f"{ours}.{fc}.bias"] = take(f"{hf}.{hf_fc}.bias", False)
    for lin in ("linear_1", "linear_2"):
        mapped[f"multi_modal_projector.{lin}.weight"] = take(
            f"multi_modal_projector.{lin}.weight", True)
        mapped[f"multi_modal_projector.{lin}.bias"] = take(
            f"multi_modal_projector.{lin}.bias", False)
    leftovers = [k for k in rest if k not in consumed]
    if leftovers:
        raise ValueError(
            f"load_hf_llava: checkpoint tensors this model cannot "
            f"represent: {leftovers[:5]}"
            f"{'...' if len(leftovers) > 5 else ''}")
    missing, unexpected = model.set_state_dict(mapped)
    assert not unexpected, unexpected
    # the language-model keys were loaded by load_hf_llama above and are
    # legitimately absent from `mapped`; only vision/projector keys must
    # be fully covered here
    vision_missing = [m for m in missing
                      if m.startswith(("vision_tower",
                                       "multi_modal_projector"))]
    if vision_missing:
        raise KeyError(
            f"load_hf_llava: model keys not covered: {vision_missing[:5]}")
    return model


def llava_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a LlavaForConditionalGeneration from a transformers Llava
    model (or a raw state dict + config). Text-config overrides go in
    ``text_overrides={...}``."""
    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    cfg = _hf_config_to_llava(hf_config, **config_overrides)
    return load_hf_llava(LlavaForConditionalGeneration(cfg), state)
