"""Mixtral (8x7B / 8x22B) sparse-MoE decoder family.

Role parity: the reference's MoE training stack (SURVEY §2.7 EP/MoE;
`/root/reference/python/paddle/incubate/distributed/models/moe/moe_layer.py`)
serves exactly this class of all-sparse top-2 decoders; PaddleNLP ships a
mixtral modeling on it. Here the family is the LlamaMoE trunk specialized
the Mixtral way:

- every layer sparse (``first_k_dense_replace=0``), NO shared expert;
- top-2 of 8 routing with softmax over the selected logits — numerically
  identical to softmax-over-all + top-k renormalization, i.e. the trunk's
  ``norm_topk_prob=True`` path;
- SwiGLU experts (HF w1=gate, w3=up, w2=down → the fused gate‖up grouped
  GEMM layout), bias-free GQA attention, optional causal sliding window.

``mixtral_from_hf`` converts a transformers ``MixtralForCausalLM`` via the
shared grouped loader with the ``block_sparse_moe``/w1-w3-w2 key scheme.
"""
from __future__ import annotations

import dataclasses

from .llama import _hf_get, mapped_rope_scaling
from .llama_moe import (LlamaMoEConfig, LlamaMoEForCausalLM,
                        load_hf_grouped_moe)


@dataclasses.dataclass
class MixtralConfig(LlamaMoEConfig):
    # Mixtral-8x7B shape
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 32768
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-5
    n_routed_experts: int = 8
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 14336
    n_shared_experts: int = 0              # no shared expert
    first_k_dense_replace: int = 0         # every layer is sparse
    norm_topk_prob: bool = True            # softmax over the top-2 logits
    # the released Mixtral-8x7B config.json ships 0.02 (the HF CLASS
    # default is 0.001; the mapper below follows the class default)
    router_aux_loss_coef: float = 0.02

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=256,
                    dtype="float32", n_routed_experts=4,
                    num_experts_per_tok=2, moe_intermediate_size=64,
                    n_shared_experts=0, first_k_dense_replace=0)
        base.update(kw)
        return MixtralConfig(**base)


class MixtralForCausalLM(LlamaMoEForCausalLM):
    """Mixtral causal LM — all-sparse LlamaMoE decoder, no shared expert,
    renormalized top-k combine."""

    def __init__(self, config: MixtralConfig):
        if config.n_shared_experts:
            raise ValueError("Mixtral has no shared expert "
                             "(n_shared_experts=0)")
        if not config.norm_topk_prob:
            raise ValueError(
                "Mixtral softmaxes over the selected top-k logits "
                "(norm_topk_prob=True)")
        if config.first_k_dense_replace:
            raise ValueError("Mixtral is sparse from layer 0 "
                             "(first_k_dense_replace=0)")
        super().__init__(config)


def _hf_config_to_mixtral(hf_config, **overrides) -> MixtralConfig:
    get = _hf_get(hf_config)
    kw = dict(
        rope_scaling=mapped_rope_scaling(get),
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        # dense intermediate mirrors the expert width (no dense layers
        # exist, but LlamaMLP shapes derive from it)
        intermediate_size=get("intermediate_size"),
        moe_intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads"),
        max_position_embeddings=get("max_position_embeddings"),
        rms_norm_eps=get("rms_norm_eps", 1e-5),
        rope_theta=get("rope_theta", 1e6),
        sliding_window=get("sliding_window"),
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
        n_routed_experts=get("num_local_experts"),
        num_experts_per_tok=get("num_experts_per_tok"),
        router_aux_loss_coef=get("router_aux_loss_coef", 0.001),
    )
    kw.update(overrides)
    return MixtralConfig(**kw)


def load_hf_mixtral(model: MixtralForCausalLM,
                    hf_state_dict) -> MixtralForCausalLM:
    """Pack a transformers MixtralForCausalLM state dict into the grouped
    layout (block_sparse_moe router; per-expert w1/w3/w2 = gate/up/down)."""
    return load_hf_grouped_moe(model, hf_state_dict,
                               who="load_hf_mixtral",
                               mlp_key="block_sparse_moe",
                               expert_keys=("w1", "w3", "w2"))


def mixtral_from_hf(hf_model_or_state, hf_config=None, **config_overrides):
    """Build a MixtralForCausalLM from a transformers model (or raw state
    dict + config)."""
    if hf_config is None:
        hf_config = hf_model_or_state.config
        state = hf_model_or_state.state_dict()
    else:
        state = hf_model_or_state
    cfg = _hf_config_to_mixtral(hf_config, **config_overrides)
    return load_hf_mixtral(MixtralForCausalLM(cfg), state)
