"""paddle.metric parity (python/paddle/metric/metrics.py): Metric base +
Accuracy, Precision, Recall, Auc — numpy-accumulated between steps (the
reference likewise accumulates on host)."""
from __future__ import annotations

import abc

import numpy as np

from ..tensor_class import Tensor


def _np(x):
    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Optional pre-processing run on device outputs; default passthrough
        (metrics.py Metric.compute)."""
        return args


class Accuracy(Metric):
    """metrics.py Accuracy parity (top-k)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        pred_idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        return (pred_idx == l[..., None]).astype(np.float32)

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
        self.count += num

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / self.count if self.count else 0.0 for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (metrics.py Precision)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(int)
        l = _np(labels).reshape(-1).astype(int)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(int)
        l = _np(labels).reshape(-1).astype(int)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via the reference's histogram-bucket approach
    (metrics.py Auc: num_thresholds buckets)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:  # [N, 2] softmax → positive-class prob
            p = p[:, 1]
        l = _np(labels).reshape(-1).astype(int)
        idx = np.clip((p * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx, (l == 1).astype(np.int64))
        np.add.at(self._stat_neg, idx, (l == 0).astype(np.int64))

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            auc += self._stat_neg[i] * (tot_pos + self._stat_pos[i] / 2.0)
            tot_pos += self._stat_pos[i]
            tot_neg += self._stat_neg[i]
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional paddle.metric.accuracy parity."""
    import paddle_tpu as paddle

    m = Accuracy(topk=(k,))
    c = m.compute(input, label)
    m.update(c)
    return paddle.to_tensor(np.float32(m.accumulate()))
