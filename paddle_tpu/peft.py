"""LoRA parameter-efficient fine-tuning.

Role parity: the PEFT/LoRA layer family the reference ecosystem ships for
LLM fine-tuning (PaddleNLP peft.lora — LoRALinear wrapping a frozen base
projection with trainable low-rank A/B factors).

TPU-native design: freezing is expressed through ``stop_gradient`` — the
jit TrainStep already splits functional state into trainable params vs
buffers on exactly that bit, so a LoRA-wrapped model compiles into a step
that differentiates ONLY the adapters while the frozen base weights ride
along as buffers (no wasted backward FLOPs on frozen projections beyond
the activation grads that must flow through them). ``merge_lora`` folds
B·A back into the base weight for deployment (zero-overhead inference).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax.numpy as jnp

from .nn.layer import Layer
from .nn.layers_common import Linear
from .tensor_class import Parameter, unwrap
from .ops.registry import apply


@dataclasses.dataclass
class LoRAConfig:
    r: int = 8
    lora_alpha: int = 16
    lora_dropout: float = 0.0
    # leaf attribute names to wrap (the attention/MLP projections)
    target_modules: Sequence[str] = ("q_proj", "k_proj", "v_proj", "o_proj")
    # also train layers whose PARAMETER name contains one of these substrings
    # (e.g. ("norm",) to keep norms trainable like PaddleNLP's modules_to_save)
    modules_to_save: Sequence[str] = ()


class LoRALinear(Layer):
    """y = base(x) + (alpha/r) * dropout(x) @ A @ B with the base frozen.

    A [in, r] Gaussian-initialized, B [r, out] zero-initialized, so the
    wrapped layer starts EXACTLY equal to the base layer."""

    def __init__(self, base: Linear, r: int, lora_alpha: int = 16,
                 lora_dropout: float = 0.0):
        super().__init__()
        if r <= 0:
            raise ValueError("LoRA rank r must be positive")
        self.base = base
        self.r = int(r)
        self.scaling = float(lora_alpha) / float(r)
        self.lora_dropout = float(lora_dropout)
        in_f = int(base.weight.shape[0])
        out_f = int(base.weight.shape[1])
        base.weight.stop_gradient = True
        if getattr(base, "bias", None) is not None:
            base.bias.stop_gradient = True
        dt = base.weight.dtype
        import jax

        from .framework import random as _random

        a0 = (jax.random.normal(_random.next_key(), (in_f, self.r), jnp.float32)
              * (1.0 / math.sqrt(self.r)))
        self.lora_A = Parameter(a0.astype(dt))
        self.lora_B = Parameter(jnp.zeros((self.r, out_f), dt))

    def forward(self, x):
        out = self.base(x)
        scale = self.scaling

        def delta(h, a, b):
            return (h @ a) @ b * scale

        h = x
        if self.lora_dropout > 0.0 and self.training:
            from .nn.functional import dropout as _dropout

            h = _dropout(h, p=self.lora_dropout, training=True)
        return out + apply("lora_delta", delta, h, self.lora_A, self.lora_B)

    def effective_weight(self):
        """The adapter-folded weight W + (alpha/r)·A·B as a LIVE tensor —
        for consumers that contract against the raw weight instead of
        calling forward (the MLA absorbed decode path reads kv_b_proj's
        weight directly); differentiable through A/B, so adapters train
        even when the host layer never calls forward().

        Cost note: the fold re-materializes the full weight at every call.
        Inside a jitted scan decode XLA hoists it (loop-invariant), but
        the host-loop decode pays it per step per layer — for adapter
        SERVING, ``merge_lora`` first and decode the merged model.

        ``lora_dropout`` acts on the INPUT (``dropout(x)·A·B``) and has no
        weight-space equivalent, so a training-mode fold would silently
        skip the regularization other adapters get — raise instead."""
        if self.lora_dropout > 0.0 and self.training:
            raise NotImplementedError(
                "effective_weight() cannot apply lora_dropout (an "
                "input-space op); use lora_dropout=0 for weight-consuming "
                "target modules, or eval() the model first")
        return self.base.weight + (self.lora_A @ self.lora_B) * self.scaling

    def merge(self) -> Linear:
        """Fold the adapter into the base weight; returns the base layer."""
        w = unwrap(self.base.weight)
        delta = (unwrap(self.lora_A).astype(jnp.float32)
                 @ unwrap(self.lora_B).astype(jnp.float32)) * self.scaling
        self.base.weight.set_value((w.astype(jnp.float32) + delta).astype(w.dtype))
        self.base.weight.stop_gradient = False
        if getattr(self.base, "bias", None) is not None:
            self.base.bias.stop_gradient = False
        return self.base

    def extra_repr(self):
        return f"r={self.r}, scaling={self.scaling}"


def get_peft_model(model, config: LoRAConfig):
    """Wrap ``config.target_modules`` Linears with LoRALinear IN PLACE and
    freeze every other parameter (except ``modules_to_save`` matches).
    Returns (model, n_wrapped)."""
    from .nn.utils import replace_sublayers

    # remember the user's pre-LoRA freeze state so merge_lora can RESTORE
    # it instead of blanket-unfreezing (a user-frozen embedding must stay
    # frozen after merge). Stacked get_peft_model calls keep the FIRST
    # snapshot — the later call would otherwise record the all-frozen
    # post-LoRA state and merge_lora would freeze the whole model.
    pre_freeze = getattr(model, "_peft_pre_freeze", None)
    if pre_freeze is None:
        pre_freeze = {name: p.stop_gradient
                      for name, p in model.named_parameters()}
    targets = tuple(config.target_modules)
    n = replace_sublayers(
        model,
        lambda name, sub: isinstance(sub, Linear) and name in targets,
        lambda sub: LoRALinear(sub, r=config.r, lora_alpha=config.lora_alpha,
                               lora_dropout=config.lora_dropout))
    if n == 0:
        raise ValueError(
            f"get_peft_model: no Linear matched target_modules="
            f"{tuple(config.target_modules)}")
    object.__setattr__(model, "_peft_pre_freeze", pre_freeze)
    keep = tuple(config.modules_to_save)
    for pname, p in model.named_parameters():
        if "lora_A" in pname or "lora_B" in pname:
            p.stop_gradient = False
        elif keep and any(k in pname for k in keep):
            p.stop_gradient = False
        else:
            p.stop_gradient = True
    return model, n


def merge_lora(model):
    """Fold every LoRALinear back into its base Linear IN PLACE (deployment
    form: zero adapter overhead, plain Linears). Returns (model, n_merged)."""
    from .nn.utils import replace_sublayers

    n = replace_sublayers(
        model,
        lambda name, sub: isinstance(sub, LoRALinear),
        lambda sub: sub.merge())
    # restore the user's PRE-LoRA freeze state (recorded by get_peft_model);
    # params that didn't exist then (none after a merge) default to trainable
    pre = getattr(model, "_peft_pre_freeze", None) or {}
    for name, p in model.named_parameters():
        p.stop_gradient = bool(pre.get(name, False))
    if hasattr(model, "_peft_pre_freeze"):
        object.__delattr__(model, "_peft_pre_freeze")
    return model, n


def lora_state_dict(model):
    """Only the adapter tensors (the checkpoint a LoRA fine-tune ships)."""
    return {k: v for k, v in model.state_dict().items()
            if "lora_A" in k or "lora_B" in k}
