"""paddle.save / paddle.load parity.

Reference: python/paddle/framework/io.py:773 (save), :1020 (load) — pickled
nested containers of tensors. Tensors serialize as numpy arrays; on load
they come back as paddle_tpu Tensors (or stay numpy with return_numpy=True).
"""
from __future__ import annotations

import io
import os
import pickle

import numpy as np

from .tensor_class import Tensor, Parameter


class _TensorPayload:
    """Pickle surrogate for device tensors."""

    __slots__ = ("array", "is_param", "name", "stop_gradient")

    def __init__(self, array, is_param, name, stop_gradient):
        self.array = array
        self.is_param = is_param
        self.name = name
        self.stop_gradient = stop_gradient


def _encode(obj):
    if isinstance(obj, Tensor):
        arr = obj.numpy()
        # bfloat16 has no numpy dtype guaranteed pickle-stable; ship as u16 view
        if str(obj.dtype) == "bfloat16":
            return _TensorPayload(("bf16", arr.view(np.uint16) if arr.dtype != np.uint16 else arr),
                                  isinstance(obj, Parameter), obj.name, obj.stop_gradient)
        return _TensorPayload(arr, isinstance(obj, Parameter), obj.name, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_encode(v) for v in obj)
    return obj


def _decode(obj, return_numpy=False):
    import jax.numpy as jnp
    import ml_dtypes

    if isinstance(obj, _TensorPayload):
        arr = obj.array
        if isinstance(arr, tuple) and arr[0] == "bf16":
            arr = arr[1].view(ml_dtypes.bfloat16)
        if return_numpy:
            return arr
        t = Parameter(jnp.asarray(arr), name=obj.name) if obj.is_param else Tensor(jnp.asarray(arr))
        t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _decode(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_decode(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    if hasattr(path, "write"):
        pickle.dump(_encode(obj), path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_encode(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    if hasattr(path, "read"):
        return _decode(pickle.load(path), return_numpy)
    with open(path, "rb") as f:
        return _decode(pickle.load(f), return_numpy)
