"""paddle.tensorrt parity surface. TensorRT is a CUDA-only engine; on the
TPU build the equivalent deployment path is StableHLO + XLA (jit.save /
static.save_inference_model), so the conversion entry points raise with
that guidance — reference behavior on a build without TRT.
"""
from __future__ import annotations

__all__ = ["Input", "TensorRTConfig", "convert", "convert_loaded_model"]


class Input:
    """Shape spec for a conversion input (min/opt/max shapes)."""

    def __init__(self, min_input_shape=None, optim_input_shape=None,
                 max_input_shape=None, input_data_type=None, name=None):
        self.min_input_shape = min_input_shape
        self.optim_input_shape = optim_input_shape
        self.max_input_shape = max_input_shape
        self.input_data_type = input_data_type
        self.name = name


class TensorRTConfig:
    def __init__(self, inputs=None, **kwargs):
        self.inputs = list(inputs or [])
        self.__dict__.update(kwargs)


def _no_trt():
    raise RuntimeError(
        "TensorRT is not available in the TPU build (CUDA-only engine). "
        "Deploy with paddle.jit.save / paddle.static.save_inference_model "
        "— the StableHLO artifact compiles with XLA on the target device.")


def convert(model, config=None, **kwargs):
    _no_trt()


def convert_loaded_model(model_dir, config=None, **kwargs):
    _no_trt()
