"""paddle.utils parity: flags, deprecated-API decorator, dlpack, unique
names, layer helpers (python/paddle/utils/)."""
from __future__ import annotations

from . import flags  # noqa: F401
from . import dlpack  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401


def try_import(module_name: str):
    """python/paddle/utils/lazy_import.py parity."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            f"{module_name} is required but not installed; the TPU image "
            f"bakes no extra pip packages") from e


def run_check():
    """paddle.utils.run_check parity: verify the install can compute."""
    import numpy as np

    import paddle_tpu as paddle

    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    b = paddle.matmul(a, a)
    assert float(b.numpy()[0, 0]) == 2.0
    n = paddle.device.device_count() if hasattr(paddle, "device") else 1
    print(f"PaddleTPU works! device check OK ({n} device(s)).")


class unique_name:
    """paddle.utils.unique_name parity (python/paddle/utils/unique_name.py)."""

    _counters = {}

    @classmethod
    def generate(cls, key: str) -> str:
        idx = cls._counters.get(key, 0)
        cls._counters[key] = idx + 1
        return f"{key}_{idx}"

    @classmethod
    def guard(cls, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            saved = dict(cls._counters)
            cls._counters.clear()
            try:
                yield
            finally:
                cls._counters.clear()
                cls._counters.update(saved)

        return _guard()


# dlpack lives in utils/dlpack.py (module), delegating to the top-level
# modern-protocol implementation; name re-exports for compat
from .dlpack import from_dlpack, to_dlpack  # noqa: E402,F401


def deprecated(update_to="", since="", reason="", level=0):
    """paddle.utils.deprecated (python/paddle/utils/deprecated.py): decorator
    that warns (level<=1) or raises (level==2) on use of a deprecated API."""
    import functools
    import warnings

    def decorator(fn):
        msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if reason:
            msg += f", {reason}"
        if update_to:
            msg += f". Use '{update_to}' instead."

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        wrapper.__deprecated_message__ = msg
        return wrapper

    return decorator


def require_version(min_version: str, max_version=None):
    """paddle.utils.require_version (python/paddle/utils/install_check.py
    sibling): check the installed framework version is in range."""
    import paddle_tpu as paddle

    def tup(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = tup(paddle.__version__)
    if tup(min_version) > cur:
        raise Exception(
            f"installed version {paddle.__version__} < required "
            f"{min_version}")
    if max_version is not None and tup(max_version) < cur:
        raise Exception(
            f"installed version {paddle.__version__} > allowed "
            f"{max_version}")
    return True
