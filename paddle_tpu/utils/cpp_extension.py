"""User extension mechanism: custom ops and native (C++) extensions.

Reference parity: ``paddle.utils.cpp_extension`` (the JIT ``load`` path
compiling user C++/CUDA into loadable ops) + the custom-op registration ABI
(paddle/phi/api/ext/op_meta_info.h PD_BUILD_OP) + the custom-device plugin
runtime (paddle/phi/backends/custom/custom_device.cc).

TPU-native mapping — three extension points:

1. :func:`register_custom_op` — the PD_BUILD_OP analog. A user supplies a
   pure-jax (or Pallas) implementation plus an optional custom VJP pair;
   the op lands in the global registry (AMP / NaN-check / tape / static
   capture all apply) and a paddle-style eager function is returned.
   Pallas kernels are first-class here: pass a function built on
   ``pl.pallas_call`` and it compiles into the surrounding XLA program —
   this IS the "custom kernel" path on TPU.

2. :func:`load` — the cpp_extension.load analog. Compiles user C++ sources
   with g++ into a cached shared library and returns the ctypes handle
   (the reference returns an imported module of ops; here native code is
   host-side by definition, so the handle exposes the raw symbols).

3. :func:`register_host_op` — bridges a host function (e.g. a ctypes
   symbol from :func:`load`, or any Python/numpy code) into jit-traced
   programs via ``jax.pure_callback`` — the TPU equivalent of a custom CPU
   kernel invoked from the executor.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# 1. custom ops (PD_BUILD_OP analog)
# ---------------------------------------------------------------------------

def register_custom_op(name: str, fn: Callable, vjp_fwd: Optional[Callable] = None,
                       vjp_bwd: Optional[Callable] = None,
                       differentiable: bool = True, doc: str = ""):
    """Register a user op and return its paddle-style eager function.

    fn: pure jax implementation ``(*arrays, **attrs) -> array(s)`` — jnp,
        lax, or a Pallas ``pallas_call`` kernel.
    vjp_fwd/vjp_bwd: optional ``jax.custom_vjp`` pair. ``vjp_fwd`` returns
        ``(out, residuals)``; ``vjp_bwd(residuals, cotangent)`` returns the
        input cotangents tuple. Without them jax differentiates ``fn``.

    The op is visible in ``paddle_tpu.ops.registry.OPS`` (so the op-suite
    completeness gate will demand a spec or tested_by for in-tree uses) and
    dispatches through ``apply`` like every built-in op.
    """
    from ..ops.registry import OPS, register_op, apply

    if name in OPS:
        raise ValueError(f"op {name!r} is already registered")
    impl = fn
    if vjp_fwd is not None:
        if vjp_bwd is None:
            raise ValueError("vjp_fwd requires vjp_bwd")
        impl = jax.custom_vjp(fn)
        impl.defvjp(vjp_fwd, vjp_bwd)

    def public(*args, **kwargs):
        kwargs.pop("name", None)
        return apply(name, impl, *args, differentiable=differentiable,
                     **kwargs)

    public.__name__ = name
    public.raw = impl
    register_op(name, impl, differentiable=differentiable, doc=doc)
    return public


# ---------------------------------------------------------------------------
# 2. native extension build (cpp_extension.load analog)
# ---------------------------------------------------------------------------

def _cache_dir() -> str:
    d = os.environ.get("PADDLE_TPU_CACHE",
                       os.path.expanduser("~/.cache/paddle_tpu"))
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str, sources: Sequence[str], extra_cflags: Sequence[str] = (),
         extra_ldflags: Sequence[str] = (), verbose: bool = False) -> ctypes.CDLL:
    """Compile user C++ sources into a cached shared library and load it.

    Parity: paddle.utils.cpp_extension.load (JIT path). The cache key is
    the digest of the source contents + flags, so edits rebuild and
    identical builds are reused across processes.
    """
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join([*extra_cflags, *extra_ldflags]).encode())
    so = os.path.join(_cache_dir(), f"lib{name}_{h.hexdigest()[:16]}.so")
    if not os.path.exists(so):
        tmp = so + f".build.{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               *extra_cflags, "-o", tmp, *sources, *extra_ldflags]
        try:
            subprocess.run(cmd, check=True, capture_output=not verbose)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = ""
            if isinstance(e, subprocess.CalledProcessError) and e.stderr:
                detail = "\n" + e.stderr.decode(errors="replace")[-2000:]
            raise RuntimeError(
                f"extension build failed ({' '.join(cmd)}){detail}") from e
        os.replace(tmp, so)
    return ctypes.CDLL(so)


# ---------------------------------------------------------------------------
# 3. host ops inside jit (custom CPU kernel analog)
# ---------------------------------------------------------------------------

def register_host_op(name: str, host_fn: Callable, out_shape_fn: Callable,
                     differentiable: bool = False, doc: str = ""):
    """Register an op whose implementation runs ON HOST (numpy / ctypes),
    callable from eager AND jit-traced code via ``jax.pure_callback``.

    host_fn: ``(*numpy_arrays, **attrs) -> numpy array(s)``.
    out_shape_fn: ``(*abstract_args, **attrs) -> ShapeDtypeStruct(s)`` —
        the InferMeta role: jit needs shapes before the host runs.
    """

    def fn(*arrays, **attrs):
        import functools

        result_shape = out_shape_fn(*arrays, **attrs)
        return jax.pure_callback(
            functools.partial(host_fn, **attrs), result_shape, *arrays)

    return register_custom_op(name, fn, differentiable=differentiable,
                              doc=doc)


# ---------------------------------------------------------------------------
# setuptools-style surface (python/paddle/utils/cpp_extension/ parity)
# ---------------------------------------------------------------------------

def get_build_directory(verbose=False) -> str:
    """Where JIT-built user extensions are cached (PADDLE_EXTENSION_DIR
    analog)."""
    import os

    d = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


def CppExtension(sources, *args, **kwargs):
    """Describe a C++ extension for setup() (reference returns a
    setuptools.Extension; here the build happens through `load`, so the
    descriptor just carries the sources/flags)."""
    return {"sources": list(sources), "kind": "cpp", "args": args,
            "kwargs": kwargs}


def CUDAExtension(sources, *args, **kwargs):
    """Accepted for API compatibility; .cu sources cannot build on the TPU
    image (no nvcc) and raise at setup() time."""
    return {"sources": list(sources), "kind": "cuda", "args": args,
            "kwargs": kwargs}


def setup(name=None, ext_modules=None, **kwargs):
    """Build the described extensions NOW with the g++ JIT path (`load`)
    and return the loaded modules keyed by name — the reference's
    setuptools command collapses to an eager build (no pip install step
    exists in this environment)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) \
        else [ext_modules] if ext_modules else []
    built = {}
    for i, ext in enumerate(exts):
        if not isinstance(ext, dict):
            raise TypeError("setup: pass CppExtension(...) descriptors")
        if ext["kind"] == "cuda":
            raise RuntimeError(
                "CUDAExtension cannot build on the TPU image (no nvcc); "
                "port the kernel to a Pallas custom op "
                "(utils.cpp_extension.register_custom_op)")
        # unique module key per extension — a shared `name` must not let
        # later extensions overwrite earlier ones
        mod_name = name if (name and len(exts) == 1) \
            else f"{name or 'ext'}_{i}"
        built[mod_name] = load(name=mod_name, sources=ext["sources"],
                               extra_cflags=tuple(
                                   ext["kwargs"].get("extra_compile_args")
                                   or ()))
    return built
