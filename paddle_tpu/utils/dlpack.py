"""paddle.utils.dlpack parity (python/paddle/utils/dlpack.py)."""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    import paddle_tpu as paddle

    return paddle.to_dlpack(x)


def from_dlpack(ext):
    import paddle_tpu as paddle

    return paddle.from_dlpack(ext)
