"""Global flag registry.

Reference parity: paddle/common/flags.h:38-94 (PD_DEFINE_* registry; every
flag settable via env FLAGS_xxx, paddle.set_flags, or pybind) — here an
absl-style Python registry (SURVEY.md §5 "TPU equivalent: absl-style flags
+ a dataclass strategy object"). Flags are read at TRACE time (jit treats
them as constants), matching how the reference's C++ reads them at kernel
launch.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, Iterator, Optional


class _Flag:
    __slots__ = ("name", "default", "value", "help", "typ")

    def __init__(self, name, default, help_str):
        self.name = name
        self.default = default
        self.typ = type(default)
        self.help = help_str
        env = os.environ.get(name)
        self.value = self._parse(env) if env is not None else default

    def _parse(self, raw):
        if self.typ is bool:
            return str(raw).lower() in ("1", "true", "yes", "on")
        return self.typ(raw)


_REGISTRY: Dict[str, _Flag] = {}

# thread-local flag overlay: a reader sees its own overrides ON TOP of
# the global registry, without mutating it. Flags are read at trace time
# and jax traces on the calling thread, so an audit/replay thread can
# retrace the reference path (fused tail off) while the engine thread's
# traces keep seeing the live flag values — flipping the global would
# race every concurrent trace.
_TLS = threading.local()


def _overrides() -> Dict[str, Any]:
    return getattr(_TLS, "overrides", None) or {}


def define_flag(name: str, default: Any, help_str: str = "") -> None:
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    if name not in _REGISTRY:
        _REGISTRY[name] = _Flag(name, default, help_str)


def get_flags(name: Optional[object] = None) -> Dict[str, Any]:
    """paddle.get_flags parity: str or list of str → {name: value}."""
    ov = _overrides()
    if name is None:
        return {k: ov.get(k, f.value) for k, f in _REGISTRY.items()}
    names = [name] if isinstance(name, str) else list(name)
    out = {}
    for n in names:
        key = n if n.startswith("FLAGS_") else "FLAGS_" + n
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        out[n] = ov.get(key, _REGISTRY[key].value)
    return out


@contextlib.contextmanager
def flag_overrides(d: Dict[str, Any]) -> Iterator[None]:
    """Override flags for THIS THREAD only, for the duration of the
    with-block. Unknown flag names raise up front (same contract as
    set_flags); values are coerced through the flag's parser. Nesting
    stacks — the inner block wins, the outer overlay is restored on
    exit."""
    layer = {}
    for n, v in d.items():
        key = n if n.startswith("FLAGS_") else "FLAGS_" + n
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        f = _REGISTRY[key]
        layer[key] = f._parse(v) if isinstance(v, str) else f.typ(v)
    prev = getattr(_TLS, "overrides", None)
    _TLS.overrides = dict(prev or {}, **layer)
    try:
        yield
    finally:
        _TLS.overrides = prev


def set_flags(d: Dict[str, Any]) -> None:
    """paddle.set_flags parity."""
    for n, v in d.items():
        key = n if n.startswith("FLAGS_") else "FLAGS_" + n
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        f = _REGISTRY[key]
        f.value = f._parse(v) if isinstance(v, str) else f.typ(v)


def flag(name: str) -> Any:
    """Fast internal read (honors the thread-local overlay)."""
    key = name if name.startswith("FLAGS_") else "FLAGS_" + name
    ov = getattr(_TLS, "overrides", None)
    if ov and key in ov:
        return ov[key]
    return _REGISTRY[key].value


# ---- core flags (the subset of the reference's ~hundreds that has meaning
# on the TPU build; each cites its reference definition site) -----------------
define_flag("check_nan_inf", False,
            "check every op output for NaN/Inf (paddle/fluid/eager/nan_inf_utils.cc)")
define_flag("benchmark", False,
            "sync after every op for timing (paddle/phi/core/flags.cc benchmark)")
define_flag("use_autotune", True,
            "enable kernel autotune cache (paddle/phi/kernels/autotune/)")
define_flag("use_fused_decode_tail", False,
            "fuse the S=1 decode tail (norm->qkv->rope and "
            "o_proj->residual->norm) into the ops/pallas/decode_tail "
            "megakernels; off = the discrete reference kernels (exact "
            "parity, read at trace time like every flag)")
define_flag("allocator_strategy", "auto_growth",
            "allocator strategy name; informational on TPU (XLA owns HBM)")
define_flag("embedding_deterministic", False,
            "deterministic embedding grad accumulation "
            "(paddle/phi/kernels/gpu/embedding_grad_kernel.cu FLAGS_embedding_deterministic)")
define_flag("cudnn_deterministic", False,
            "map to XLA deterministic reductions where applicable")
define_flag("log_memory_stats", False,
            "log live/peak device memory at step boundaries (memory/stats.cc)")
define_flag("lock_witness", False,
            "instrument cross-thread locks with the runtime lock-order "
            "witness (paddle_tpu/analysis/threads/witness.py): records "
            "per-thread acquisition order, validates it against the "
            "static lock graph, emits lock.order_violation flight-"
            "recorder events and rides incident bundles; off = plain "
            "threading locks, zero overhead")
define_flag("collective_static_check", False,
            "verify shape/dtype agreement across processes before eager "
            "collectives (paddle/phi/core/distributed/check/static_check.cc)")
