"""Open-loop load harness: drive a serving target at a scheduled rate
and measure what the SLOs actually got.

Open-loop means arrivals follow the TRACE clock, not the target's
responses — a saturated server keeps receiving requests exactly like
production traffic, which is the only way the admission bound, deadline
shedding and priority ordering ever get exercised. Each scheduled
request runs on its own (named) thread: POST ``/v1/completions`` with
``stream: true``, measure TTFT and inter-token gaps off the SSE chunks,
and classify the outcome — completed, 429 (bounded queue / capacity
shed, with its Retry-After), 504 (``code=deadline_exceeded``), 5xx
(always a bug: the saturation gate pins this at zero), timeout (a
silent stall — also pinned at zero), or a planned client cancel.

:func:`summarize` folds outcomes into the report the ROADMAP asks for —
p50/p99 TTFT, inter-token latency, **goodput-under-SLO** (completions
whose first token landed inside their budget), shed/429/504 rates, and
deltas of the stack's own counters (admitted / finished / rejected /
shed / deadline misses / preempted / migrated) read from ``/health``
before and after. :func:`sweep` walks a QPS ladder and
:func:`find_knee` locates the saturation knee — the highest offered
rate the target still serves at ≥ ``threshold`` goodput.
"""
from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Sequence
from urllib.parse import urlsplit

from ..distributed.log_utils import get_logger
from .trace import TraceRequest, trace_digest
from .workload import WorkloadSpec, synthesize

__all__ = ["Outcome", "run_schedule", "summarize", "stack_stats",
           "alerts_state", "sweep", "find_knee", "run_workload"]

#: the stack counters the harness reads before/after a run (summed over
#: every live worker when the target is the cluster router)
_STACK_KEYS = ("requests_admitted", "requests_finished",
               "requests_cancelled", "requests_rejected", "requests_shed",
               "deadline_misses", "requests_preempted",
               "requests_migrated_out", "requests_migrated_in",
               "requests_degraded", "tokens_generated",
               # cluster-level self-healing counters: summed from the
               # router's supervisor section (zero on unsupervised /
               # single-process stacks) — a load report shows how many
               # worker restarts and quarantines the traffic window saw
               "worker_restarts", "requests_quarantined")


class Outcome:
    """What one scheduled request actually experienced."""

    __slots__ = ("index", "priority", "slo_ms", "t_sched", "lag_s",
                 "status", "clean", "cancelled", "timed_out", "error",
                 "code", "retry_after", "ttft_s", "gaps", "n_tokens")

    def __init__(self, index: int, tr: TraceRequest):
        self.index = index
        self.priority = tr.priority
        self.slo_ms = tr.slo_ms
        self.t_sched = tr.t
        self.lag_s = 0.0       # dispatch lag vs the trace clock
        self.status: Optional[int] = None
        self.clean = False
        self.cancelled = False
        self.timed_out = False
        self.error: Optional[str] = None
        self.code: Optional[str] = None
        self.retry_after: Optional[str] = None
        self.ttft_s: Optional[float] = None
        self.gaps: List[float] = []
        self.n_tokens = 0

    @property
    def in_slo(self) -> bool:
        """Completed clean with the first token inside the SLO budget
        (requests without an SLO count when they complete) — the
        goodput predicate."""
        if not (self.status == 200 and self.clean):
            return False
        if self.slo_ms is None or self.ttft_s is None:
            return self.slo_ms is None
        return self.ttft_s * 1000.0 <= self.slo_ms

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


def _host_port(url: str):
    u = urlsplit(url if "//" in url else f"http://{url}")
    return u.hostname or "127.0.0.1", int(u.port or 80)


def _one_request(host: str, port: int, tr: TraceRequest, out: Outcome,
                 timeout: float):
    body = {"prompt_token_ids": tr.prompt_token_ids,
            "max_tokens": tr.max_tokens, "stream": True,
            "priority": tr.priority}
    if tr.slo_ms is not None:
        body["slo_ms"] = tr.slo_ms
    # the constructor never raises (connect is lazy, on request())
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    t_sent = time.perf_counter()
    try:
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        out.status = resp.status
        if resp.status != 200:
            raw = resp.read()
            out.retry_after = resp.getheader("Retry-After")
            try:
                parsed = json.loads(raw)
                out.error = parsed.get("error")
                out.code = parsed.get("code")
            except ValueError:
                out.error = raw.decode(errors="replace")
            return
        t_last = None
        while True:
            line = resp.readline()
            if not line:
                break               # EOF without [DONE]: not clean
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):].strip()
            if payload == b"[DONE]":
                out.clean = True
                break
            d = json.loads(payload)
            if "error" in d:
                out.error = str(d["error"])
                out.code = d.get("code")
                break
            if "migrated" in d:
                # a drain moved the stream and no relay is following it
                # (direct-to-worker target); treat like an unclean end
                out.error = "migrated"
                break
            now = time.perf_counter()
            if out.n_tokens == 0:
                out.ttft_s = now - t_sent
            elif t_last is not None:
                out.gaps.append(now - t_last)
            t_last = now
            out.n_tokens += 1
            if (tr.cancel_after_s is not None
                    and now - t_sent >= tr.cancel_after_s):
                out.cancelled = True
                break               # close the socket mid-stream
    except (TimeoutError, http.client.HTTPException, OSError) as e:
        if isinstance(e, (TimeoutError,)) or "timed out" in str(e):
            out.timed_out = True
        out.error = f"{type(e).__name__}: {e}"
    except Exception as e:
        # e.g. a malformed SSE payload (json.loads above): the outcome
        # must record the failure — a dead request thread would count
        # as a clean-looking 200 in the aggregate
        out.error = f"{type(e).__name__}: {e}"
    finally:
        conn.close()


def run_schedule(url: str, schedule: Sequence[TraceRequest], *,
                 stream_timeout: float = 60.0,
                 join_timeout: Optional[float] = None) -> List[Outcome]:
    """Drive ``schedule`` against ``url`` open-loop. Returns one Outcome
    per scheduled request (same order). The dispatcher sleeps to each
    arrival offset and spawns the request regardless of how many are
    still in flight — saturation is the point, not an error."""
    host, port = _host_port(url)
    ordered = sorted(range(len(schedule)), key=lambda i: schedule[i].t)
    outcomes = [Outcome(i, tr) for i, tr in enumerate(schedule)]
    threads = []
    t0 = time.perf_counter()
    for i in ordered:
        tr = schedule[i]
        delay = tr.t - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        outcomes[i].lag_s = max(0.0, (time.perf_counter() - t0) - tr.t)
        th = threading.Thread(
            target=_one_request, args=(host, port, tr, outcomes[i],
                                       stream_timeout),
            name=f"loadgen-req-{i}", daemon=True)
        threads.append(th)
        th.start()
    deadline = time.monotonic() + (join_timeout if join_timeout is not None
                                   else stream_timeout + 10.0)
    for th in threads:
        th.join(timeout=max(0.0, deadline - time.monotonic()))
        if th.is_alive():
            get_logger().warning(
                "loadgen: request thread %s still alive past the join "
                "deadline (counted as timed out)", th.name)
    for out, th in zip((outcomes[i] for i in ordered), threads):
        if th.is_alive():
            out.timed_out = True
    return outcomes


def _pcts(vals: List[float]) -> Dict[str, float]:
    import numpy as np

    if not vals:
        return {"p50": None, "p99": None}
    a = np.asarray(vals, float) * 1000.0
    return {"p50": round(float(np.percentile(a, 50)), 3),
            "p99": round(float(np.percentile(a, 99)), 3)}


def _bucket(outs: Sequence[Outcome], duration_s: float) -> dict:
    completed = [o for o in outs if o.status == 200 and o.clean]
    good = [o for o in outs if o.in_slo]
    return {
        "n": len(outs),
        "completed": len(completed),
        "rejected_429": sum(1 for o in outs if o.status == 429),
        "shed_504": sum(1 for o in outs if o.status == 504),
        "http_5xx": sum(1 for o in outs
                        if o.status is not None and o.status >= 500
                        and o.status != 504),
        "midstream_error": sum(1 for o in outs if o.status == 200
                               and not o.clean and not o.cancelled
                               and not o.timed_out),
        "cancelled": sum(1 for o in outs if o.cancelled),
        "timed_out": sum(1 for o in outs if o.timed_out),
        "untyped": sum(1 for o in outs
                       if o.status not in (200, 429, 504)),
        "ttft_ms": _pcts([o.ttft_s for o in completed
                          if o.ttft_s is not None]),
        "inter_token_ms": _pcts([g for o in completed for g in o.gaps]),
        "goodput": {
            "requests": len(good),
            "ratio": round(len(good) / len(outs), 4) if outs else None,
            "requests_per_s": round(len(good) / duration_s, 3),
            "tokens_per_s": round(sum(o.n_tokens for o in good)
                                  / duration_s, 1),
        },
    }


def summarize(outcomes: Sequence[Outcome], duration_s: float,
              offered_qps: Optional[float] = None,
              stack_before: Optional[dict] = None,
              stack_after: Optional[dict] = None,
              digest: Optional[str] = None) -> dict:
    """Fold a run's outcomes into the capacity report: overall and
    per-priority-class latency/goodput/shed buckets, plus the stack's
    own counter deltas when /health snapshots were taken."""
    report = _bucket(outcomes, duration_s)
    report["offered_qps"] = offered_qps
    report["duration_s"] = duration_s
    report["schedule_digest"] = digest
    prios = sorted({o.priority for o in outcomes})
    report["by_priority"] = {
        str(p): _bucket([o for o in outcomes if o.priority == p],
                        duration_s)
        for p in prios}
    if stack_before is not None and stack_after is not None:
        report["stack"] = {
            k: stack_after.get(k, 0) - stack_before.get(k, 0)
            for k in _STACK_KEYS}
    return report


def stack_stats(url: str, timeout: float = 10.0) -> dict:
    """Sum the serving stack's stats() counters behind ``url``: a
    single-process server reports them on its own /health; the cluster
    router's /health names every live worker, and each worker's /health
    carries its engine's stats — the SAME counters either way, so load
    reports read one schema."""
    def _get(u):
        with urllib.request.urlopen(u, timeout=timeout) as r:
            return json.loads(r.read())

    totals = {k: 0 for k in _STACK_KEYS}
    try:
        payload = _get(url.rstrip("/") + "/health")
    except (OSError, ValueError) as e:
        get_logger().warning("loadgen: /health read failed (%s: %s)",
                             type(e).__name__, e)
        return totals
    sources = []
    if "workers" in payload:
        sup = payload.get("supervisor") or {}
        totals["worker_restarts"] = int(sup.get("restarts_total", 0) or 0)
        totals["requests_quarantined"] = len(sup.get("quarantined", ()))
        for w in payload["workers"].values():
            if not w.get("alive"):
                continue
            try:
                sources.append(_get(w["url"] + "/health"))
            except (OSError, ValueError) as e:
                get_logger().warning(
                    "loadgen: worker /health read failed (%s: %s)",
                    type(e).__name__, e)
    else:
        sources.append(payload)
    for src in sources:
        stats = src.get("stats") or {}
        for k in _STACK_KEYS:
            totals[k] += int(stats.get(k, 0) or 0)
    return totals


def alerts_state(url: str, timeout: float = 10.0) -> dict:
    """One ``GET /alerts`` read folded to what a load run cares about:
    which alerts are firing and how many transitions the alerting layer
    has made — a saturation run that trips (or fails to trip) an SLO
    alert is a harness-visible fact, not something to eyeball on a
    dashboard afterwards."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/alerts",
                                    timeout=timeout) as r:
            payload = json.loads(r.read())
    except (OSError, ValueError) as e:
        get_logger().warning("loadgen: /alerts read failed (%s: %s)",
                             type(e).__name__, e)
        return {"enabled": False, "firing": [], "transitions_total": 0,
                "transitions": []}
    return {"enabled": bool(payload.get("enabled")),
            "firing": list(payload.get("firing") or ()),
            "transitions_total": int(payload.get("transitions_total", 0)),
            "transitions": [
                {k: t.get(k) for k in ("alert", "from", "to", "t")}
                for t in payload.get("transitions") or ()]}


def run_workload(url: str, spec: WorkloadSpec, *,
                 stream_timeout: float = 60.0) -> dict:
    """Synthesize + run + summarize one spec (the sweep's unit step).
    The summary carries the schedule digest so repeat runs are provably
    over the same traffic."""
    schedule = synthesize(spec)
    digest = trace_digest(schedule)
    before = stack_stats(url)
    outcomes = run_schedule(url, schedule, stream_timeout=stream_timeout)
    after = stack_stats(url)
    return summarize(outcomes, spec.duration_s, offered_qps=spec.qps,
                     stack_before=before, stack_after=after,
                     digest=digest)


def _wait_idle(url: str, timeout: float = 30.0):
    """Best-effort drain barrier between sweep points: poll /health
    until no requests are active or queued anywhere, so point N+1
    measures its own QPS rather than point N's backlog."""
    deadline = time.monotonic() + timeout
    host = url.rstrip("/")
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(host + "/health", timeout=5) as r:
                payload = json.loads(r.read())
        except (OSError, ValueError):
            return
        if "workers" in payload:
            busy = sum(w.get("active", 0) + w.get("queued", 0)
                       for w in payload["workers"].values()
                       if w.get("alive"))
        else:
            busy = payload.get("active", 0) + payload.get("queued", 0)
        if not busy:
            return
        time.sleep(0.1)


def find_knee(points: Sequence[dict], threshold: float = 0.85) -> float:
    """The saturation knee: the highest offered QPS whose goodput ratio
    stays >= ``threshold`` (points below the knee serve what they are
    offered; past it, sheds/429s/late TTFTs eat the margin). Falls back
    to the lowest measured QPS when every point is past saturation."""
    knee = None
    for p in sorted(points, key=lambda p: p["offered_qps"]):
        ratio = (p["goodput"]["ratio"] or 0.0)
        if ratio >= threshold:
            knee = p["offered_qps"]
        else:
            break
    return knee if knee is not None else min(
        p["offered_qps"] for p in points)


def sweep(url: str, spec: WorkloadSpec, qps_list: Sequence[float], *,
          threshold: float = 0.85, stream_timeout: float = 60.0,
          settle_s: float = 30.0) -> dict:
    """QPS sweep: run ``spec`` at each offered rate (same seed — the
    schedules differ only by rate), locate the knee, and return
    ``{"points": [...], "knee_qps": ...}`` — the capacity curve
    scheduler/kernel/quantization PRs cite instead of anecdotes."""
    points = []
    for q in qps_list:
        summary = run_workload(url, spec.replace(qps=float(q)),
                               stream_timeout=stream_timeout)
        points.append(summary)
        _wait_idle(url, timeout=settle_s)
    return {"points": points,
            "knee_qps": find_knee(points, threshold=threshold)}
