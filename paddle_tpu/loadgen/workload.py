"""Seeded workload synthesis: arrival processes + request mixes.

A :class:`WorkloadSpec` describes traffic statistically — arrival
process (Poisson / uniform / on-off burst), prompt/output-length ranges,
a priority/SLO class mix, a cancel rate — and :func:`synthesize`
materialises it into a concrete :class:`~.trace.TraceRequest` schedule
from ONE seed. The same (spec, seed) always yields the byte-identical
schedule (pinned in tier-1): replay is only a referee if two runs
provably saw the same traffic.
"""
from __future__ import annotations

import random
from typing import List, Optional, Sequence, Tuple

from .trace import TraceRequest

__all__ = ["WorkloadSpec", "synthesize"]

#: (priority, slo_ms | None, weight) — the default mix is one default-
#: priority class with no SLO (pure FIFO traffic)
ClassMix = Tuple[int, Optional[float], float]

_PROCESSES = ("poisson", "uniform", "burst")


class WorkloadSpec:
    """Statistical description of an open-loop request stream.

    ``qps`` is the mean offered rate over ``duration_s``. ``burst``
    traffic alternates ``burst_on_s`` of Poisson arrivals at
    ``qps * burst_factor`` with ``burst_off_s`` of silence (mean rate
    stays ~``qps`` when on/off windows are equal and factor is 2).
    ``classes`` is a weighted list of ``(priority, slo_ms, weight)``;
    ``cancel_rate`` marks that fraction of requests for a mid-stream
    client disconnect after ``cancel_after_s`` (uniform in the range).
    """

    __slots__ = ("qps", "duration_s", "process", "burst_on_s",
                 "burst_off_s", "burst_factor", "prompt_tokens",
                 "max_tokens", "classes", "cancel_rate",
                 "cancel_after_s", "vocab_size", "seed")

    def __init__(self, qps: float, duration_s: float,
                 process: str = "poisson",
                 burst_on_s: float = 1.0, burst_off_s: float = 1.0,
                 burst_factor: float = 2.0,
                 prompt_tokens: Tuple[int, int] = (4, 12),
                 max_tokens: Tuple[int, int] = (4, 12),
                 classes: Sequence[ClassMix] = ((1, None, 1.0),),
                 cancel_rate: float = 0.0,
                 cancel_after_s: Tuple[float, float] = (0.05, 0.5),
                 vocab_size: int = 512, seed: int = 0):
        if process not in _PROCESSES:
            raise ValueError(f"process must be one of {_PROCESSES}, "
                             f"got {process!r}")
        if qps <= 0 or duration_s <= 0:
            raise ValueError("qps and duration_s must be > 0")
        if not classes or any(w <= 0 for _, _, w in classes):
            raise ValueError("classes need positive weights")
        self.qps = float(qps)
        self.duration_s = float(duration_s)
        self.process = process
        self.burst_on_s = float(burst_on_s)
        self.burst_off_s = float(burst_off_s)
        self.burst_factor = float(burst_factor)
        self.prompt_tokens = (int(prompt_tokens[0]), int(prompt_tokens[1]))
        self.max_tokens = (int(max_tokens[0]), int(max_tokens[1]))
        self.classes = tuple((int(p), None if s is None else float(s),
                              float(w)) for p, s, w in classes)
        self.cancel_rate = float(cancel_rate)
        self.cancel_after_s = (float(cancel_after_s[0]),
                               float(cancel_after_s[1]))
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)

    def replace(self, **kw) -> "WorkloadSpec":
        d = {k: getattr(self, k) for k in self.__slots__}
        d.update(kw)
        return WorkloadSpec(**d)


def _arrivals(spec: WorkloadSpec, rng: random.Random) -> List[float]:
    t, out = 0.0, []
    if spec.process == "uniform":
        gap = 1.0 / spec.qps
        t = gap
        while t < spec.duration_s:
            out.append(t)
            t += gap
        return out
    if spec.process == "poisson":
        while True:
            t += rng.expovariate(spec.qps)
            if t >= spec.duration_s:
                return out
            out.append(t)
    # burst: Poisson at qps*burst_factor, arrivals outside the on-window
    # of the (on+off) cycle are discarded — mean rate scales with the
    # duty cycle, peaks probe the admission bound
    cycle = spec.burst_on_s + spec.burst_off_s
    while True:
        t += rng.expovariate(spec.qps * spec.burst_factor)
        if t >= spec.duration_s:
            return out
        if (t % cycle) < spec.burst_on_s:
            out.append(t)


def _pick_class(spec: WorkloadSpec, rng: random.Random) -> ClassMix:
    total = sum(w for _, _, w in spec.classes)
    x = rng.random() * total
    for p, s, w in spec.classes:
        x -= w
        if x <= 0:
            return (p, s, w)
    return spec.classes[-1]


def synthesize(spec: WorkloadSpec) -> List[TraceRequest]:
    """Materialise the spec into a concrete schedule. Deterministic:
    every random choice comes from one ``random.Random(spec.seed)``
    stream, so the same spec yields the byte-identical trace."""
    rng = random.Random(spec.seed)
    schedule = []
    for t in _arrivals(spec, rng):
        plo, phi = spec.prompt_tokens
        plen = rng.randint(plo, max(plo, phi))
        ids = [rng.randrange(1, spec.vocab_size) for _ in range(plen)]
        mlo, mhi = spec.max_tokens
        max_toks = rng.randint(mlo, max(mlo, mhi))
        prio, slo_ms, _ = _pick_class(spec, rng)
        cancel = None
        if spec.cancel_rate > 0 and rng.random() < spec.cancel_rate:
            clo, chi = spec.cancel_after_s
            cancel = rng.uniform(clo, chi)
        schedule.append(TraceRequest(t, ids, max_toks, priority=prio,
                                     slo_ms=slo_ms,
                                     cancel_after_s=cancel))
    return schedule
