"""The replayable trace format: one JSONL line per request.

A trace is the *schedule* of an open-loop load run — when each request
arrives (seconds from run start) and exactly what it asks for — fully
materialised so the same file drives the same byte-identical request
sequence against any target (the single-process ``serving_http`` server
or the cluster router; both speak ``POST /v1/completions``). Recorded
traces and synthesized ones (:mod:`paddle_tpu.loadgen.workload`) share
this one format, so "replay last Tuesday's overload" and "replay the
seeded Poisson burst" are the same code path.

Line schema (sorted keys, so a dumped trace is byte-stable)::

    {"cancel_after_s": null, "max_tokens": 8, "priority": 1,
     "prompt_token_ids": [17, 3, ...], "slo_ms": 250.0, "t": 0.8134}

``t`` is the arrival offset; ``slo_ms``/``cancel_after_s`` are null when
absent. The loader round-trips exactly what ``dumps_trace`` wrote.
"""
from __future__ import annotations

import hashlib
import json
from typing import Iterable, List, Optional

__all__ = ["TraceRequest", "dumps_trace", "dump_trace", "loads_trace",
           "load_trace", "trace_digest"]


class TraceRequest:
    """One scheduled request of an open-loop run."""

    __slots__ = ("t", "prompt_token_ids", "max_tokens", "priority",
                 "slo_ms", "cancel_after_s")

    def __init__(self, t: float, prompt_token_ids, max_tokens: int,
                 priority: int = 1, slo_ms: Optional[float] = None,
                 cancel_after_s: Optional[float] = None):
        self.t = float(t)
        self.prompt_token_ids = [int(x) for x in prompt_token_ids]
        self.max_tokens = int(max_tokens)
        self.priority = int(priority)
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        self.cancel_after_s = (None if cancel_after_s is None
                               else float(cancel_after_s))

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d: dict) -> "TraceRequest":
        return cls(**{k: d.get(k) for k in cls.__slots__
                      if d.get(k) is not None or k in ("slo_ms",
                                                       "cancel_after_s")})

    def __repr__(self):
        slo = f" slo={self.slo_ms}ms" if self.slo_ms is not None else ""
        return (f"TraceRequest(t={self.t:.3f}, "
                f"prompt={len(self.prompt_token_ids)}tok, "
                f"max={self.max_tokens}, p{self.priority}{slo})")


def dumps_trace(schedule: Iterable[TraceRequest]) -> str:
    """Serialize a schedule as JSONL with sorted keys — the SAME
    schedule always produces the SAME bytes (the determinism contract
    the replay gate pins)."""
    return "".join(json.dumps(tr.as_dict(), sort_keys=True) + "\n"
                   for tr in schedule)


def dump_trace(schedule: Iterable[TraceRequest], path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        f.write(dumps_trace(schedule))
    return path


def loads_trace(raw: str) -> List[TraceRequest]:
    out = []
    for ln in raw.splitlines():
        ln = ln.strip()
        if ln:
            out.append(TraceRequest.from_dict(json.loads(ln)))
    return out


def load_trace(path: str) -> List[TraceRequest]:
    with open(path, encoding="utf-8") as f:
        return loads_trace(f.read())


def trace_digest(schedule: Iterable[TraceRequest]) -> str:
    """sha256 over the canonical JSONL bytes: two runs replayed the same
    schedule iff their digests match (what the summary report carries so
    A/B capacity curves are provably over the same traffic)."""
    return hashlib.sha256(dumps_trace(schedule).encode()).hexdigest()
