"""paddle_tpu.loadgen — traffic replay & saturation harness.

The proof layer for "heavy traffic from millions of users": seeded
arrival synthesis (:mod:`.workload`), a replayable JSONL trace format
(:mod:`.trace`), and an open-loop HTTP driver + capacity reports +
QPS-sweep knee finder (:mod:`.harness`). Drives the single-process
``serving_http`` server and the cluster router identically (both speak
``POST /v1/completions``), and reads shed/429/preempt/migrate accounting
off the metrics the stack already exports.

CLI: ``scripts/load_replay.py``; bench leg: ``BENCH_CONFIG=load``;
runbook: docs/SERVING.md "Capacity & overload runbook".
"""
from .trace import (TraceRequest, dump_trace, dumps_trace, load_trace,
                    loads_trace, trace_digest)
from .workload import WorkloadSpec, synthesize
from .harness import (Outcome, alerts_state, find_knee, run_schedule,
                      run_workload, stack_stats, summarize, sweep)

__all__ = [
    "TraceRequest", "dump_trace", "dumps_trace", "load_trace",
    "loads_trace", "trace_digest",
    "WorkloadSpec", "synthesize",
    "Outcome", "alerts_state", "find_knee", "run_schedule",
    "run_workload", "stack_stats", "summarize", "sweep",
]
