"""Runtime fault injection: the process-local injector behind the
``chaos.on(point, ...)`` hooks compiled into kv_handoff / pool / router /
worker.

Exactly one injector (or none) is active per process. The hooks pay one
module-global read when no plan is installed — the production fast path
is a ``None`` check, the same guarded-disable idiom as the tracer and
flight recorder. With a plan installed, every arrival at a point bumps a
per-point counter under a lock; a fault whose (point, scope, nth) matches
fires ONCE, is recorded as a ``chaos.inject`` flight-recorder event in
the injecting process (so incident bundles separate fault from symptom),
and is returned to the call site, which applies the action's semantics
(drop the message, flip a byte, answer 500, pause the heartbeat, exit).

Worker subprocesses receive their plan through the environment:
``PDTPU_CHAOS_PLAN`` (JSON, or a path to a JSON file) — the launcher
exports it, ``run_worker`` calls :func:`install_from_env` with its
``worker:<replica_id>`` scope. The router/driver process installs
directly with :func:`install`.
"""
from __future__ import annotations

import os
import random
from typing import Optional

import numpy as np

from ..analysis.threads.witness import make_lock
from ..distributed.log_utils import get_logger
from ..observability import flightrecorder as _frec
from .plan import FaultPlan

__all__ = ["ChaosInjector", "active", "install", "install_from_env",
           "uninstall", "on", "corrupt_bundle", "arm_engine",
           "ENV_PLAN", "ENV_INCARNATION"]

ENV_PLAN = "PDTPU_CHAOS_PLAN"
#: the supervisor exports the respawned worker's restart generation here
#: so incarnation-scoped faults (see plan.Fault) target one life of the
#: process — a planned kill must not re-fire in the respawn it caused
ENV_INCARNATION = "PDTPU_CHAOS_INCARNATION"


class ChaosInjector:
    """Counts arrivals at injection points and fires matching faults."""

    def __init__(self, plan: FaultPlan, scope: str, incarnation: int = 0):
        self.plan = plan
        self.scope = scope
        self.incarnation = int(incarnation)
        self.rng = random.Random(plan.seed)
        self._lock = make_lock("ChaosInjector._lock")
        self._counts = {}      # point -> arrivals seen
        self._spent = set()    # indices of faults that already fired
        self._fired = []       # audit log of fired faults

    def fire(self, point: str, **ctx):
        """One arrival at ``point``; returns the matching Fault (now
        spent) or None. The caller applies the action. ``crash_on_rid``
        faults match when their ``detail`` rid is in ``ctx["rids"]``
        (the request ids entering the dispatch) instead of the arrival
        count — the poison follows the request, not the clock."""
        rids = ctx.get("rids") or ()
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            hit = None
            for i, f in enumerate(self.plan.faults):
                if (i in self._spent or f.point != point
                        or (f.scope is not None and f.scope != self.scope)
                        or (f.incarnation is not None
                            and f.incarnation != self.incarnation)):
                    continue
                if f.action == "crash_on_rid":
                    if f.detail not in rids:
                        continue
                elif f.nth != n:
                    continue
                hit = f
                self._spent.add(i)
                break
            if hit is not None:
                self._fired.append({"point": point, "action": hit.action,
                                    "nth": n, "scope": self.scope})
        if hit is None:
            return None
        rec = _frec.RECORDER
        if rec.enabled:
            rec.record(_frec.EV_CHAOS, point=point, action=hit.action,
                       nth=n, scope=self.scope, detail=hit.detail)
        get_logger().warning(
            "chaos: injecting %s at %s (arrival %s, scope %s)",
            hit.action, point, n, self.scope)
        return hit

    def fired(self):
        with self._lock:
            return list(self._fired)

    def counts(self):
        with self._lock:
            return dict(self._counts)


_ACTIVE: Optional[ChaosInjector] = None


def active() -> Optional[ChaosInjector]:
    return _ACTIVE


def install(plan: FaultPlan, scope: str,
            incarnation: int = 0) -> ChaosInjector:
    """Install ``plan`` as this process's injector (replacing any)."""
    global _ACTIVE
    _ACTIVE = ChaosInjector(plan, scope, incarnation=incarnation)
    get_logger().info("chaos: plan installed (scope %s, incarnation %s, "
                      "%d faults)", scope, incarnation, len(plan.faults))
    return _ACTIVE


def uninstall():
    global _ACTIVE
    _ACTIVE = None


def install_from_env(scope: str) -> Optional[ChaosInjector]:
    """Install the plan the launcher exported via ``PDTPU_CHAOS_PLAN``
    (inline JSON or a file path); None when the env carries no plan.
    ``PDTPU_CHAOS_INCARNATION`` (set by the supervisor on respawn)
    selects which incarnation-scoped faults arm in this process."""
    raw = os.environ.get(ENV_PLAN)
    if not raw:
        return None
    if raw.lstrip().startswith("{"):
        plan = FaultPlan.loads(raw)
    else:
        plan = FaultPlan.load(raw)
    try:
        incarnation = int(os.environ.get(ENV_INCARNATION, "0"))
    except ValueError:
        incarnation = 0
    return install(plan, scope, incarnation=incarnation)


def on(point: str, **ctx):
    """The injection hook: None on the (usual) no-plan fast path, else
    the fired Fault for this arrival (or None when nothing matches)."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj.fire(point, **ctx)


def corrupt_bundle(bundle: dict, rng: Optional[random.Random] = None) -> dict:
    """A copy of ``bundle`` with ONE byte of its first KV leaf flipped —
    applied AFTER sealing, so the receiver's checksum must catch it.
    ``rng`` (default: the active injector's seeded rng) picks the byte,
    keeping the corruption deterministic under a fixed-seed plan."""
    rng = rng or (_ACTIVE.rng if _ACTIVE is not None else None)
    out = dict(bundle)
    layers = [list(pair) for pair in bundle["layers"]]
    leaf = np.asarray(layers[0][0])
    raw = bytearray(leaf.tobytes())
    idx = rng.randrange(len(raw)) if rng is not None else len(raw) // 2
    raw[idx] ^= 0xFF
    layers[0][0] = np.frombuffer(bytes(raw),
                                 dtype=leaf.dtype).reshape(leaf.shape)
    out["layers"] = layers
    return out


def arm_engine(engine, injector: Optional[ChaosInjector] = None):
    """Wrap ``engine.step`` with the ``worker.step`` injection point when
    the plan carries one (``kill`` exits the process at the nth decode
    step — SIGKILL-grade, no teardown). No-op otherwise: the decode hot
    loop only pays the wrapper when a step fault is actually planned."""
    inj = injector if injector is not None else _ACTIVE
    if inj is None or "worker.step" not in inj.plan.points():
        return engine
    orig = engine.step

    def step(*a, **kw):
        f = inj.fire("worker.step")
        if f is not None and f.action == "kill":
            get_logger().warning(
                "chaos: planned kill at engine step — exiting now")
            os._exit(137)
        return orig(*a, **kw)

    engine.step = step
    return engine
