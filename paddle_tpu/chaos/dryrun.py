"""Chaos dryrun: the seeded end-to-end robustness gate.

Launches the REAL multi-process cluster (router + worker subprocesses
over TCPStore leases and shm handoff rings), installs a fixed-seed
:class:`~.plan.FaultPlan` in every process, drives concurrent streamed
completions through the router while the plan injects worker death,
handoff loss/corruption, a heartbeat stall and router↔worker 5xx — and
checks the claims the serving tier makes about itself:

- every stream completes **token-identical** to a solo run and ends with
  a clean ``[DONE]``;
- **zero client-visible 5xx** for absorbable faults (everything in the
  default plan is absorbable: retries, failover and handoff re-export
  must hide them);
- corrupt bundles are **detected** (checksum → ``HandoffCorrupt``) and
  retried, never admitted; dropped bundles time out and re-place;
- a stalled heartbeat reaps the worker and a fresh lease **rejoins** it;
- the cluster watchtower **judges** the kills: the
  ``worker_restart_rate`` objective (second-scale windows via
  ``alert_time_scale``) must FIRE while the supervisor restarts workers
  and RESOLVE after the heal — ``report["alerts"]`` carries the
  transition evidence off the router's ``/alerts``.

``scripts/chaos_dryrun.py`` is the CLI over :func:`run_dryrun`; the
tier-1 chaos gate (tests/test_chaos.py) drives it directly and asserts
on the returned report.
"""
from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.request
from typing import List, Optional

from ..distributed.log_utils import get_logger
from . import inject as _inject
from .plan import Fault, FaultPlan

__all__ = ["default_plan", "run_dryrun"]


#: the request id the default plan's poison fault triggers on — the
#: dryrun submits one request carrying it and asserts the quarantine
#: contains the blast radius at <= 2 workers + exactly one typed 422
POISON_RID = "poison-rid"


def default_plan(seed: int = 0) -> FaultPlan:
    """The gate plan: one seeded plan combining every failure domain the
    cluster claims to absorb. Counts are arrivals per point per process
    (worker:0 is the prefill worker in the default topology; worker:2 a
    decode worker); kills are incarnation-scoped so the supervisor's
    respawn is not re-killed by the fault that killed its predecessor."""
    return FaultPlan(seed=seed, faults=[
        # the 2nd KV bundle worker:0 ships is silently lost — the decode
        # side must 504 and the router re-place (fresh prefill, fresh
        # bundle)
        Fault("kv_handoff.send", "drop", nth=2, scope="worker:0"),
        # the 4th is corrupted by one flipped byte AFTER sealing — the
        # admitting engine must refuse it with HandoffCorrupt, and the
        # router absorb the 5xx
        Fault("kv_handoff.send", "corrupt", nth=4, scope="worker:0"),
        # one placement hop fails as if the worker answered 500
        Fault("router.upstream", "http_500", nth=6, scope="router"),
        # worker:0's lease heartbeat stalls past its ttl (process alive,
        # membership lapsed): the pool must reap it, traffic must flow
        # without it, and the fresh post-stall stamp must rejoin it
        Fault("worker.request", "stall_heartbeat", nth=3,
              scope="worker:0", duration_s=4.0),
        # a decode worker dies at its 20th engine step — SIGKILL-grade,
        # mid-stream; relays must fail over and continue token-identical,
        # and the SUPERVISOR must restart it (incarnation 0 only)
        Fault("worker.step", "kill", nth=20, scope="worker:2",
              incarnation=0),
        # the DOUBLE-KILL: the restarted worker:2 dies again at its 5th
        # step (incarnation 1 only) — the supervisor restarts it a
        # second time and the pool still heals to full strength
        Fault("worker.step", "kill", nth=5, scope="worker:2",
              incarnation=1),
        # the POISON: whichever worker (any incarnation) lets POISON_RID
        # into a decode dispatch dies there — quarantine must contain it
        # at <= 2 worker deaths and answer the client a typed 422
        Fault("engine.dispatch", "crash_on_rid", detail=POISON_RID,
              scope=None, incarnation=None),
    ])


def _stream_completion(host, port, body, timeout=300):
    """POST a streaming completion; returns (status, clean, tokens)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            resp.read()
            return resp.status, False, []
        toks, clean = [], False
        while True:
            line = resp.readline()
            if not line:
                break
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):].strip()
            if payload == b"[DONE]":
                clean = True
                break
            d = json.loads(payload)
            if "error" in d or "migrated" in d:
                break
            toks.append(d["choices"][0]["token_ids"][0])
        return 200, clean, toks
    finally:
        conn.close()


def _get_json(url, timeout=15):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def run_dryrun(plan: Optional[FaultPlan] = None, *, streams: int = 4,
               max_tokens: int = 32, prompt_len: int = 9,
               layers: int = 2, max_batch: int = 8, max_len: int = 128,
               page_size: int = 8, ttl: float = 1.5,
               handoff_wait_s: float = 3.0, max_retries: int = 5,
               compile_cache: Optional[str] = None,
               stream_timeout: float = 420.0,
               load_qps: float = 0.0,
               load_duration_s: float = 4.0,
               heal_timeout: float = 150.0,
               poison: bool = True) -> dict:
    """Run the fixed-seed chaos plan against a real 1-prefill + 2-decode
    SUPERVISED cluster and return the report dict (see module docstring
    for the claims it checks; ``report["ok"]`` is the verdict).

    With ``load_qps > 0`` the plan additionally fires UNDER GENERATED
    LOAD: a seeded open-loop Poisson stream (paddle_tpu.loadgen, with a
    priority/SLO class mix) drives the router concurrently with the
    hand-built gate streams, and ``report["load"]`` carries the harness
    summary — every load outcome must be typed (200 / 429 / 504 with
    ``code=deadline_exceeded``), zero 5xx, zero silent stalls, and the
    shed accounting must balance (requests_shed == deadline_misses when
    no bounded queue displaces work).

    Since the self-healing PR the dryrun is the full
    kill→restart→heal→quarantine story: after the classic fault window
    it (a) waits for the supervisor to restart the killed worker and the
    pool to return to full strength, (b) drives sequential streams until
    the plan's DOUBLE-KILL fires in the restarted incarnation and heals
    again, (c) submits the plan's POISON request (``POISON_RID``) and
    asserts it kills at most ``QUARANTINE_THRESHOLD`` workers before the
    router refuses it with a typed 422 ``code=request_quarantined``
    (``poison=False`` skips this leg), and (d) replays a post-heal
    loadgen burst asserting the healed tier still serves at the offered
    rate with typed-only outcomes — capacity recovered, not merely
    survived."""
    import numpy as np

    import paddle_tpu as paddle
    from ..models.llama import LlamaConfig, LlamaForCausalLM
    from ..observability import flightrecorder as frec
    from ..serving_cluster import launch_cluster

    plan = plan or default_plan()
    cache = compile_cache or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache")
    cfg = {
        "cluster": {"host": "127.0.0.1", "port": 0, "ttl": ttl,
                    "platform": "cpu", "compile_cache": cache,
                    "handoff_wait_s": handoff_wait_s,
                    "max_retries": max_retries,
                    "model_name": "tiny-llama-chaos",
                    # cluster watchtower at gate speed: sample fast and
                    # scale the alert windows from minutes to seconds so
                    # the worker-restart objective's fire->resolve cycle
                    # completes INSIDE the dryrun (window 12s, resolve
                    # hold 1s at scale 0.1)
                    "ts_interval_s": 0.25,
                    "alert_time_scale": 0.1},
        # fast healing for the gate: short backoff (the compile cache is
        # warm by restart time), generous breaker budget (the plan kills
        # worker:2 twice ON PURPOSE — the breaker must contain loops,
        # not the planned chaos), quick health-reset
        "supervisor": {"backoff_base_s": 0.25, "backoff_max_s": 2.0,
                       "breaker_threshold": 6, "breaker_window_s": 120.0,
                       "healthy_reset_s": 5.0},
        "model": {"kind": "tiny_llama", "num_hidden_layers": layers,
                  "seed": 0},
        "engine": {"max_batch": max_batch, "max_len": max_len,
                   "page_size": page_size},
        "workers": [{"role": "prefill", "count": 1},
                    {"role": "decode", "count": 2}],
    }

    # the reference run: same seed + spec as the workers build
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=layers))
    rng = np.random.RandomState(plan.seed + 3)
    prompts = [rng.randint(1, 512, (prompt_len,)).tolist()
               for _ in range(streams)]
    solos = [model.generate(paddle.to_tensor(np.asarray(p)[None]),
                            max_new_tokens=max_tokens).numpy()[0].tolist()
             for p in prompts]

    rec = frec.get_recorder()
    rec.enable()
    since = rec.stats()["recorded"]
    os.environ[_inject.ENV_PLAN] = plan.dumps()
    injector = _inject.install(plan, scope="router")
    cluster = None
    try:
        cluster = launch_cluster(cfg)
        host, port = cluster.address
        # one sequential warm request compiles the prefill/export bucket
        # before the concurrent phase, so the handoff_wait_s clock runs
        # against transport time, not first-compile time. The plan's
        # counters see it (it is arrival #1 at each point) — no default
        # fault triggers at nth=1.
        conn = http.client.HTTPConnection(host, port, timeout=300)
        try:
            conn.request("POST", "/v1/completions",
                         json.dumps({"prompt_token_ids": prompts[0],
                                     "max_tokens": 1}),
                         {"Content-Type": "application/json"})
            warm = conn.getresponse()
            warm.read()
        finally:
            conn.close()
        if warm.status != 200:
            raise RuntimeError(
                f"chaos dryrun warmup failed: {warm.status}")

        # generated load UNDER the fault plan (not idle hand-built
        # streams): an open-loop seeded mix with SLO classes runs
        # concurrently with the gate streams below, so the kill / drop
        # / corrupt / stall / 5xx faults fire while real traffic flows
        load_outcomes: List = []
        load_thread = None
        load_before = None
        if load_qps > 0:
            from ..loadgen import (WorkloadSpec, run_schedule,
                                   stack_stats, synthesize)

            load_spec = WorkloadSpec(
                qps=load_qps, duration_s=load_duration_s,
                process="poisson", prompt_tokens=(4, prompt_len),
                max_tokens=(4, 12),
                classes=((0, None, 0.4), (1, 8000.0, 0.4),
                         (2, 2500.0, 0.2)),
                vocab_size=512, seed=plan.seed + 11)
            load_schedule = synthesize(load_spec)
            load_before = stack_stats(f"http://{host}:{port}")

            def _drive_load():
                try:
                    load_outcomes.extend(run_schedule(
                        f"http://{host}:{port}", load_schedule,
                        stream_timeout=stream_timeout))
                except Exception as e:
                    # a dead load generator must show up in the report
                    # as missing outcomes, not as a hung thread the
                    # join below silently abandons
                    get_logger().warning(
                        "chaos dryrun: background load failed (%s: %s)",
                        type(e).__name__, e)

            load_thread = threading.Thread(target=_drive_load,
                                           name="chaos-loadgen",
                                           daemon=True)
            load_thread.start()
        results: List[Optional[tuple]] = [None] * streams

        def client(i):
            try:
                results[i] = _stream_completion(
                    host, port,
                    {"prompt_token_ids": prompts[i],
                     "max_tokens": max_tokens, "stream": True},
                    timeout=stream_timeout)
            except Exception as e:
                # a None result already means "stream failed" to the
                # gate checks below — record why instead of dying with
                # the verdict unexplained
                get_logger().warning(
                    "chaos dryrun: gate stream %d failed (%s: %s)",
                    i, type(e).__name__, e)

        threads = [threading.Thread(target=client, args=(i,),
                                    name=f"chaos-client-{i}")
                   for i in range(streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=stream_timeout)

        # kill-leg guarantee: placement races can starve the kill target
        # of decode work in a light run (its only stream was the dropped
        # bundle, say) — feed sequential streams until its per-process
        # step counter crosses the plan's nth and the kill fires. These
        # must be absorbed exactly like the planned ones: the failover
        # replays them on the survivor, token-identical.
        mopup_ok = True
        for _ in range(10):
            if cluster.processes[2].poll() is not None:
                break
            st, cl, tk = _stream_completion(
                host, port, {"prompt_token_ids": prompts[0],
                             "max_tokens": 24, "stream": True},
                timeout=stream_timeout)
            mopup_ok = (mopup_ok and st == 200 and cl
                        and tk == solos[0][:24])

        # the stalled worker must rejoin on its fresh post-pause lease
        rejoined = False
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and not rejoined:
            try:
                health = _get_json(f"http://{host}:{port}/health")
            except OSError:
                break
            w0 = health["workers"].get("0")
            rejoined = bool(w0 and w0["alive"])
            if not rejoined:
                time.sleep(0.5)

        # wind down the generated-load phase and read the stack's shed
        # accounting off the survivors' /health counters
        load_report = None
        if load_thread is not None:
            from ..loadgen import stack_stats, summarize

            load_thread.join(timeout=stream_timeout)
            load_after = stack_stats(f"http://{host}:{port}")
            load_report = summarize(load_outcomes, load_duration_s,
                                    offered_qps=load_qps,
                                    stack_before=load_before,
                                    stack_after=load_after)

        # ---- self-healing: kill -> restart -> heal -> quarantine -----
        def _alive_count() -> int:
            try:
                h = _get_json(f"http://{host}:{port}/health")
            except OSError:
                return 0
            return sum(1 for w in h["workers"].values() if w["alive"])

        def _wait_healed(n: int, timeout: float) -> bool:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if _alive_count() >= n:
                    return True
                time.sleep(0.4)
            return False

        sup = cluster.supervisor
        n_workers = 3

        # heal #1: the supervisor restarts the killed decode worker
        # (same replica id, fresh lease + port) and the pool returns to
        # full strength — capacity recovered without an operator
        healed_after_kill = _wait_healed(n_workers, heal_timeout)

        # the DOUBLE-KILL: drive sequential streams until the plan's
        # incarnation-1 kill fires in the restarted worker:2 and the
        # supervisor restarts it a SECOND time; every driven stream must
        # be absorbed token-identical exactly like the planned kill
        double_kill_streams_ok = True
        restarts_w2 = 0
        dk_deadline = time.monotonic() + heal_timeout
        while time.monotonic() < dk_deadline:
            w2 = (sup.state()["workers"].get("2") or {}) if sup else {}
            restarts_w2 = len(w2.get("restarts") or ())
            if restarts_w2 >= 2:
                break
            st, cl, tk = _stream_completion(
                host, port, {"prompt_token_ids": prompts[1],
                             "max_tokens": 16, "stream": True},
                timeout=stream_timeout)
            double_kill_streams_ok = (
                double_kill_streams_ok and st == 200 and cl
                and tk == solos[1][:16])
        healed_after_double_kill = (restarts_w2 >= 2
                                    and _wait_healed(n_workers,
                                                     heal_timeout))

        # the POISON: one request that deterministically kills whichever
        # engine dispatches it. The quarantine must contain the blast
        # radius at <= 2 workers and answer the CLIENT a typed 422 —
        # exactly one, never a retry loop across the whole tier
        poison_report = None
        healed_after_poison = True
        if poison and sup is not None:
            conn = http.client.HTTPConnection(host, port,
                                              timeout=stream_timeout)
            try:
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt_token_ids": prompts[0],
                                "max_tokens": 8,
                                "request_id": POISON_RID}),
                    {"Content-Type": "application/json"})
                p_resp = conn.getresponse()
                try:
                    p_body = json.loads(p_resp.read() or b"{}")
                except ValueError:
                    p_body = {}
            finally:
                conn.close()
            ledger = sup.ledger.snapshot()
            quarantine_rec = ledger["quarantined"].get(POISON_RID) or {}
            poison_report = {
                "status": p_resp.status,
                "code": p_body.get("code"),
                "deaths": len(ledger["implicated"].get(POISON_RID, ())),
                "replicas": quarantine_rec.get("replicas"),
                "quarantined": sorted(ledger["quarantined"]),
            }
            healed_after_poison = _wait_healed(n_workers, heal_timeout)

        # post-heal capacity: replay a seeded open-loop burst at the
        # same offered rate against the HEALED tier — goodput at the
        # pre-fault knee, typed-only outcomes, zero 5xx (capacity
        # recovered, not merely survived)
        post_heal = None
        if load_qps > 0:
            from ..loadgen import (WorkloadSpec, run_schedule, summarize,
                                   synthesize)

            heal_spec = WorkloadSpec(
                qps=load_qps, duration_s=2.5, process="poisson",
                prompt_tokens=(4, prompt_len), max_tokens=(4, 10),
                vocab_size=512, seed=plan.seed + 23)
            heal_outs = run_schedule(
                f"http://{host}:{port}", synthesize(heal_spec),
                stream_timeout=stream_timeout)
            post_heal = summarize(heal_outs, 2.5, offered_qps=load_qps)
        # ---- watchtower referee: the worker-restart objective must
        # have FIRED during the kill legs (the supervisor's restarts
        # land in worker_restarts_total, the federated store samples
        # it, the cluster AlertManager judges it) and RESOLVED once the
        # scaled window drained after the heal — fire->resolve proven
        # end to end, not asserted from unit math
        from ..loadgen import alerts_state

        alerts_report = None
        restart_fired = restart_resolved = False
        alert_deadline = time.monotonic() + 30.0
        while time.monotonic() < alert_deadline:
            a = alerts_state(f"http://{host}:{port}")
            trans = a["transitions"]
            restart_fired = any(
                t["alert"] == "worker_restart_rate"
                and t["to"] == "firing" for t in trans)
            restart_resolved = restart_fired and any(
                t["alert"] == "worker_restart_rate"
                and t["to"] == "resolved" for t in trans)
            alerts_report = {
                "enabled": a["enabled"],
                "firing_final": a["firing"],
                "fired": sorted({t["alert"] for t in trans
                                 if t["to"] == "firing"}),
                "restart_fired": restart_fired,
                "restart_resolved": restart_resolved,
                "transitions": trans,
            }
            if restart_resolved or not a["enabled"]:
                break
            time.sleep(0.5)

        supervisor_state = sup.state() if sup is not None else None

        # surviving workers' chaos.inject events (the killed worker's
        # ring died with it — its evidence is the exit code below)
        fired = {"router": injector.fired()}
        try:
            health = _get_json(f"http://{host}:{port}/health")
            for rid_s, w in health["workers"].items():
                if not w["alive"]:
                    continue
                evs = _get_json(w["url"]
                                + "/debug/events?kind=chaos")["events"]
                fired[f"worker:{rid_s}"] = [
                    {k: e.get(k) for k in ("point", "action", "nth")}
                    for e in evs]
        except OSError:
            pass

        import subprocess

        killed = cluster.processes[2].poll()
        if killed is None:
            try:
                killed = cluster.processes[2].wait(timeout=10)
            except subprocess.TimeoutExpired:
                killed = None  # kill fault never fired: report says so
    finally:
        os.environ.pop(_inject.ENV_PLAN, None)
        _inject.uninstall()
        if cluster is not None:
            cluster.close()

    evs = rec.events(since=since)
    retries = [e for e in evs if e["kind"] == "router.retry"]
    lost = [e for e in evs if e["kind"] == "router.worker_lost"]
    stream_reports = []
    client_5xx = 0
    all_ok = True
    for i, r in enumerate(results):
        status, clean, toks = r if r is not None else (None, False, [])
        identical = toks == solos[i]
        if status is not None and status >= 500:
            client_5xx += 1
        ok = status == 200 and clean and identical
        all_ok = all_ok and ok
        stream_reports.append({"stream": i, "status": status,
                               "clean": clean,
                               "token_identical": identical,
                               "tokens": len(toks)})
    corrupt_detected = any("checksum mismatch" in str(e.get("reason", ""))
                           for e in retries)
    drop_detected = any("not received" in str(e.get("reason", ""))
                        for e in retries)
    drop_fired = any(f.get("action") == "drop"
                     for fs in fired.values() for f in fs)
    # a drop is ABSORBED either by its own symptom (the decode side's
    # 504 "not received" timed out and the router re-placed) or masked
    # by a concurrent failover (the waiting decode worker died inside
    # the wait window and the same re-place path took over) — both are
    # clean, and token identity above is the invariant that matters
    drop_absorbed = drop_detected or (drop_fired and all_ok)
    poison_ok = True
    if poison_report is not None:
        poison_ok = (poison_report["status"] == 422
                     and poison_report["code"] == "request_quarantined"
                     and poison_report["deaths"] <= 2
                     and poison_report["quarantined"] == [POISON_RID])
    post_heal_ok = (post_heal is None
                    or (post_heal["http_5xx"] == 0
                        and post_heal["untyped"] == 0
                        and post_heal["timed_out"] == 0
                        and post_heal["completed"] > 0))
    report = {
        "plan": plan.as_dict(),
        "streams": stream_reports,
        "client_5xx": client_5xx,
        "retries": [{k: e.get(k) for k in
                     ("replica_id", "attempt", "delivered", "reason")}
                    for e in retries],
        "worker_lost": [{"replica_id": e.get("replica_id"),
                         "reason": e.get("reason")} for e in lost],
        "faults_fired": fired,
        "corrupt_detected_and_retried": corrupt_detected,
        "drop_detected_and_retried": drop_detected,
        "drop_fired": drop_fired,
        "drop_absorbed": drop_absorbed,
        "stalled_worker_rejoined": rejoined,
        "killed_worker_exit": killed,
        "kill_mopup_ok": mopup_ok,
        "load": load_report,
        # the self-healing story
        "healed_after_kill": healed_after_kill,
        "double_kill_restarts": restarts_w2,
        "double_kill_streams_ok": double_kill_streams_ok,
        "healed_after_double_kill": healed_after_double_kill,
        "poison": poison_report,
        "healed_after_poison": healed_after_poison,
        "post_heal_load": post_heal,
        "alerts": alerts_report,
        "supervisor": supervisor_state,
        "ok": (all_ok and client_5xx == 0 and corrupt_detected
               and drop_absorbed and rejoined and bool(lost)
               and killed == 137 and mopup_ok
               and healed_after_kill and healed_after_double_kill
               and double_kill_streams_ok and poison_ok
               and healed_after_poison and post_heal_ok
               and restart_fired and restart_resolved),
    }
    return report
