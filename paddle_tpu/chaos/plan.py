"""FaultPlan: a seeded, deterministic description of the failures to
inject into a serving cluster.

A plan is data, not code: a list of :class:`Fault` specs, each naming an
**injection point** (a guarded hook compiled into the serving stack),
an **action**, the **nth arrival** at that point that should trigger it,
and the **scope** (which process injects — ``worker:<replica_id>`` or
``router``). Counting arrivals instead of sampling wall-clock makes a
plan replayable: the same plan over the same request sequence injects
the same faults, which is what lets the chaos dryrun gate assert
token-identical completions under failure.

Injection points and their legal actions:

========================  =====================================================
point                     actions
========================  =====================================================
``kv_handoff.send``       ``drop`` (bundle silently lost), ``corrupt``
                          (one byte flipped AFTER sealing — the checksum
                          must catch it), ``delay`` (``delay_s`` stall)
``router.upstream``       ``http_500`` (placement attempt fails as if the
                          worker answered 5xx), ``delay``
``worker.request``        ``http_500`` (worker answers 500),
                          ``stall_heartbeat`` (pause the lease heartbeat
                          for ``duration_s`` — process alive, membership
                          lapsed), ``delay``
``worker.step``           ``kill`` (``os._exit`` at the nth engine decode
                          step — SIGKILL-grade death, no teardown)
``engine.dispatch``       ``crash_on_rid`` (``os._exit`` the moment the
                          request id named by ``detail`` enters a decode
                          dispatch — the deterministic poison request;
                          ``nth`` is ignored, the rid IS the trigger)
``engine.logits``         ``perturb_logit`` (the nth decode step emits a
                          flipped token for its first active slot —
                          silent wrong-output drift, NOT a crash; the
                          correctness sentinel's injected-divergence
                          drill, bisectable by replay_divergence)
``pool.probe``            ``probe_fail`` (the router's /health poll of a
                          worker is treated as failed)
========================  =====================================================

**Incarnations.** Under the worker supervisor a killed worker restarts
as the same replica with a bumped *incarnation* number; the respawned
process re-installs the SAME plan from the environment. A fault's
``incarnation`` field scopes it to one life of the process: the default
``0`` fires only in the original incarnation (so a planned kill does
not re-fire in the respawned worker and crash-loop it), an explicit
integer targets that restart generation (``incarnation=1`` = the first
respawn — how the gate stages a double-kill), and ``None`` fires in any
incarnation (how ``crash_on_rid`` keeps killing whichever worker the
poison request lands on until the quarantine refuses it).

Plans serialize as JSON (``dumps``/``loads``/``load``) so the launcher
can hand one to worker subprocesses through the environment
(``PDTPU_CHAOS_PLAN``) — see :mod:`paddle_tpu.chaos.inject`.
"""
from __future__ import annotations

import json
from typing import List, Optional

__all__ = ["Fault", "FaultPlan", "POINT_ACTIONS"]

POINT_ACTIONS = {
    "kv_handoff.send": ("drop", "corrupt", "delay"),
    "router.upstream": ("http_500", "delay"),
    "worker.request": ("http_500", "stall_heartbeat", "delay"),
    "worker.step": ("kill",),
    "engine.dispatch": ("crash_on_rid",),
    "engine.logits": ("perturb_logit",),
    "pool.probe": ("probe_fail",),
}


class Fault:
    """One planned failure: fire ``action`` on the ``nth`` arrival at
    ``point`` in the process whose injector scope equals ``scope``
    (``None`` = any process that reaches the point) and whose
    ``incarnation`` matches (``0`` = the original process, ``N`` = the
    Nth supervised respawn, ``None`` = any). Each fault fires at most
    once per process. ``crash_on_rid`` faults match on the request id in
    ``detail`` instead of the arrival count."""

    __slots__ = ("point", "action", "nth", "scope", "delay_s",
                 "duration_s", "detail", "incarnation")

    def __init__(self, point: str, action: str, nth: int = 1,
                 scope: Optional[str] = None, delay_s: float = 0.0,
                 duration_s: float = 0.0, detail: Optional[str] = None,
                 incarnation: Optional[int] = 0):
        if point not in POINT_ACTIONS:
            raise ValueError(
                f"unknown injection point {point!r} "
                f"(have {sorted(POINT_ACTIONS)})")
        if action not in POINT_ACTIONS[point]:
            raise ValueError(
                f"action {action!r} is not legal at {point!r} "
                f"(legal: {POINT_ACTIONS[point]})")
        if int(nth) < 1:
            raise ValueError(f"nth is 1-based, got {nth}")
        if action == "crash_on_rid" and not detail:
            raise ValueError(
                "crash_on_rid needs detail=<request id> — the rid that "
                "poisons its dispatch")
        self.point = point
        self.action = action
        self.nth = int(nth)
        self.scope = scope
        self.delay_s = float(delay_s)
        self.duration_s = float(duration_s)
        self.detail = detail
        self.incarnation = None if incarnation is None else int(incarnation)

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d: dict) -> "Fault":
        return cls(**{k: d[k] for k in cls.__slots__ if k in d})

    def __repr__(self):
        extra = f" scope={self.scope}" if self.scope else ""
        return (f"Fault({self.action}@{self.point} nth={self.nth}"
                f"{extra})")


class FaultPlan:
    """An ordered set of faults plus the seed that makes any sampled
    choice (e.g. which byte ``corrupt`` flips) reproducible."""

    def __init__(self, faults: List[Fault], seed: int = 0):
        self.faults = list(faults)
        self.seed = int(seed)

    def as_dict(self) -> dict:
        return {"seed": self.seed,
                "faults": [f.as_dict() for f in self.faults]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls([Fault.from_dict(f) for f in d.get("faults", ())],
                   seed=d.get("seed", 0))

    def dumps(self) -> str:
        return json.dumps(self.as_dict())

    @classmethod
    def loads(cls, raw: str) -> "FaultPlan":
        return cls.from_dict(json.loads(raw))

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def points(self):
        return {f.point for f in self.faults}

    def __repr__(self):
        return f"FaultPlan(seed={self.seed}, faults={self.faults})"
