"""Deterministic chaos injection for the serving cluster.

The robustness proof layer: the cluster claims to survive worker churn,
handoff loss/corruption, heartbeat stalls and flaky transports — this
package makes those claims falsifiable by *injecting* exactly those
failures under a fixed-seed, replayable :class:`~.plan.FaultPlan`, with
every injected fault recorded as a ``chaos.inject`` flight-recorder
event so incident bundles show fault vs. symptom.

- :mod:`plan` — the fault-plan data model (points, actions, nth-arrival
  triggers, process scopes; JSON round-trip);
- :mod:`inject` — the process-local injector behind the guarded
  ``chaos.on(point, ...)`` hooks in kv_handoff / pool / router / worker
  (free when no plan is installed);
- :mod:`dryrun` — the seeded end-to-end runner: real multi-process
  cluster + concurrent clients + the plan's faults, asserting every
  stream completes token-identical with zero client-visible 5xx for
  absorbable faults. ``scripts/chaos_dryrun.py`` is the CLI; the tier-1
  chaos gate drives it from tests/test_chaos.py.

See docs/SERVING.md "Failure domains & migration runbook".
"""
from .inject import (active, arm_engine, corrupt_bundle,  # noqa: F401
                     install, install_from_env, on, uninstall)
from .plan import Fault, FaultPlan, POINT_ACTIONS        # noqa: F401

__all__ = [
    "Fault", "FaultPlan", "POINT_ACTIONS", "active", "arm_engine",
    "corrupt_bundle", "install", "install_from_env", "on", "uninstall",
]
