"""paddle.text parity (python/paddle/text/): viterbi decode + datasets.

Datasets are download-gated (no egress in the TPU image) but accept the
reference's cached-file formats from disk.
"""
from __future__ import annotations

import os

import numpy as np

from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap

__all__ = ["ViterbiDecoder", "viterbi_decode", "UCIHousing", "Imdb"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """python/paddle/text/viterbi_decode.py parity: batched Viterbi over
    emission potentials [B, L, T] with transitions [T, T] (or [T+2, T+2]
    with BOS/EOS). Returns (scores [B], paths [B, L]).

    Implemented as a lax.scan over time — jit/TPU friendly (no Python loop
    over sequence length).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fn(pot, trans, lens):
        b, l, t = pot.shape
        if include_bos_eos_tag:
            # reference semantics: tags [0..T), trans has BOS=T, EOS=T+1 rows
            bos, eos = t, t + 1
            init = pot[:, 0] + trans[bos, :t][None, :]
        else:
            init = pot[:, 0]

        def step(carry, i):
            alpha, hist_dummy = carry
            scores = alpha[:, :, None] + trans[:t, :t][None]  # [B, T, T]
            best_prev = jnp.argmax(scores, axis=1)            # [B, T]
            best_score = jnp.max(scores, axis=1) + pot[:, i]
            keep = (i < lens)[:, None]
            alpha_new = jnp.where(keep, best_score, alpha)
            bp = jnp.where(keep, best_prev, jnp.arange(t)[None, :])
            return (alpha_new, None), bp

        (alpha, _), bps = lax.scan(step, (init, None), jnp.arange(1, l))
        if include_bos_eos_tag:
            alpha = alpha + trans[:t, eos][None, :]
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1)  # [B]

        def back(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # bps[i-1] maps step-i tags → best step-(i-1) tag; walking in reverse
        # emits tags for steps l-1..1, and the final carry is step 0's tag
        first, path_rev = lax.scan(back, last, bps, reverse=True)
        paths = jnp.concatenate(
            [first[:, None], jnp.swapaxes(path_rev, 0, 1)], axis=1)  # [B, L]
        # positions beyond each length keep tag 0 (reference pads with 0)
        mask = jnp.arange(l)[None, :] < lens[:, None]
        return scores, jnp.where(mask, paths, 0)

    return apply("viterbi_decode", fn, potentials, transition_params, lengths,
                 differentiable=False, n_outputs=2)


class ViterbiDecoder:
    """python/paddle/text/viterbi_decode.py ViterbiDecoder parity."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


_NO_EGRESS = ("{name}: data file not found at {path}; this environment has "
              "no network egress — place the reference's cached dataset "
              "file there")


from ..io.dataset import Dataset  # noqa: E402


class UCIHousing(Dataset):
    """python/paddle/text/datasets/uci_housing.py parity."""

    def __init__(self, data_file=None, mode="train", download=True):
        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/uci_housing/housing.data")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="UCIHousing", path=path))
        raw = np.loadtxt(path).astype(np.float32)
        feat = raw[:, :-1]
        feat = (feat - feat.mean(0)) / np.maximum(feat.std(0), 1e-8)
        n = int(len(raw) * 0.8)
        sl = slice(0, n) if mode == "train" else slice(n, None)
        self.x = feat[sl]
        self.y = raw[sl, -1:]

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class Imdb(Dataset):
    """python/paddle/text/datasets/imdb.py parity (tokenised tar)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/imdb/aclImdb_v1.tar.gz")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="Imdb", path=path))
        import re
        import tarfile

        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                g = pat.match(m.name)
                if not g:
                    continue
                words = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower().split()
                docs.append(words)
                labels.append(0 if g.group(1) == "pos" else 1)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        vocab = {w: i for i, (w, c) in enumerate(
            sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))) if c > cutoff}
        unk = len(vocab)
        self.word_idx = vocab
        self.docs = [np.array([vocab.get(w, unk) for w in d], np.int64)
                     for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]

    def __len__(self):
        return len(self.docs)
