"""paddle.text parity (python/paddle/text/): viterbi decode + datasets.

Datasets are download-gated (no egress in the TPU image) but accept the
reference's cached-file formats from disk.
"""
from __future__ import annotations

import os

import numpy as np

from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap

__all__ = ["ViterbiDecoder", "viterbi_decode", "UCIHousing", "Imdb"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """python/paddle/text/viterbi_decode.py parity: batched Viterbi over
    emission potentials [B, L, T] with transitions [T, T] (or [T+2, T+2]
    with BOS/EOS). Returns (scores [B], paths [B, L]).

    Implemented as a lax.scan over time — jit/TPU friendly (no Python loop
    over sequence length).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fn(pot, trans, lens):
        b, l, t = pot.shape
        if include_bos_eos_tag:
            # reference semantics: tags [0..T), trans has BOS=T, EOS=T+1 rows
            bos, eos = t, t + 1
            init = pot[:, 0] + trans[bos, :t][None, :]
        else:
            init = pot[:, 0]

        def step(carry, i):
            alpha, hist_dummy = carry
            scores = alpha[:, :, None] + trans[:t, :t][None]  # [B, T, T]
            best_prev = jnp.argmax(scores, axis=1)            # [B, T]
            best_score = jnp.max(scores, axis=1) + pot[:, i]
            keep = (i < lens)[:, None]
            alpha_new = jnp.where(keep, best_score, alpha)
            bp = jnp.where(keep, best_prev, jnp.arange(t)[None, :])
            return (alpha_new, None), bp

        (alpha, _), bps = lax.scan(step, (init, None), jnp.arange(1, l))
        if include_bos_eos_tag:
            alpha = alpha + trans[:t, eos][None, :]
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1)  # [B]

        def back(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # bps[i-1] maps step-i tags → best step-(i-1) tag; walking in reverse
        # emits tags for steps l-1..1, and the final carry is step 0's tag
        first, path_rev = lax.scan(back, last, bps, reverse=True)
        paths = jnp.concatenate(
            [first[:, None], jnp.swapaxes(path_rev, 0, 1)], axis=1)  # [B, L]
        # positions beyond each length keep tag 0 (reference pads with 0)
        mask = jnp.arange(l)[None, :] < lens[:, None]
        return scores, jnp.where(mask, paths, 0)

    return apply("viterbi_decode", fn, potentials, transition_params, lengths,
                 differentiable=False, n_outputs=2)


class ViterbiDecoder:
    """python/paddle/text/viterbi_decode.py ViterbiDecoder parity."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


_NO_EGRESS = ("{name}: data file not found at {path}; this environment has "
              "no network egress — place the reference's cached dataset "
              "file there")


from ..io.dataset import Dataset  # noqa: E402


class UCIHousing(Dataset):
    """python/paddle/text/datasets/uci_housing.py parity."""

    def __init__(self, data_file=None, mode="train", download=True):
        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/uci_housing/housing.data")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="UCIHousing", path=path))
        raw = np.loadtxt(path).astype(np.float32)
        feat = raw[:, :-1]
        feat = (feat - feat.mean(0)) / np.maximum(feat.std(0), 1e-8)
        n = int(len(raw) * 0.8)
        sl = slice(0, n) if mode == "train" else slice(n, None)
        self.x = feat[sl]
        self.y = raw[sl, -1:]

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class Imdb(Dataset):
    """python/paddle/text/datasets/imdb.py parity (tokenised tar)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/imdb/aclImdb_v1.tar.gz")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="Imdb", path=path))
        import re
        import tarfile

        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                g = pat.match(m.name)
                if not g:
                    continue
                words = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower().split()
                docs.append(words)
                labels.append(0 if g.group(1) == "pos" else 1)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        vocab = {w: i for i, (w, c) in enumerate(
            sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))) if c > cutoff}
        unk = len(vocab)
        self.word_idx = vocab
        self.docs = [np.array([vocab.get(w, unk) for w in d], np.int64)
                     for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """text/datasets/imikolov.py parity: PTB n-grams from
    simple-examples.tgz."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        import collections
        import tarfile

        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/imikolov/simple-examples.tgz")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="Imikolov", path=path))
        fname = {"train": "ptb.train.txt", "valid": "ptb.valid.txt",
                 "test": "ptb.test.txt"}[mode]
        with tarfile.open(path) as tf:
            member = next(m for m in tf.getmembers()
                          if m.name.endswith(fname))
            lines = tf.extractfile(member).read().decode().splitlines()
            train_member = next(m for m in tf.getmembers()
                                if m.name.endswith("ptb.train.txt"))
            train_lines = tf.extractfile(train_member).read().decode() \
                .splitlines()
        freq = collections.Counter(
            w for ln in train_lines for w in ln.split())
        # <unk> gets the trailing id; drop a literal <unk> token first
        # (PTB text contains it) so no id exceeds len(word_idx)-1
        freq.pop("<unk>", None)
        vocab = sorted(w for w, c in freq.items() if c >= min_word_freq)
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk) for w in ln.split()]
            if data_type.upper() == "NGRAM":
                for i in range(window_size - 1, len(ids)):
                    self.data.append(np.asarray(
                        ids[i - window_size + 1:i + 1], np.int64))
            else:  # SEQ
                self.data.append(np.asarray(ids, np.int64))

    def __getitem__(self, i):
        return self.data[i]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """text/datasets/movielens.py parity: ml-1m.zip (ratings/users/movies
    .dat files, '::'-separated)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        import zipfile

        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/movielens/ml-1m.zip")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="Movielens", path=path))
        with zipfile.ZipFile(path) as zf:
            def read(name):
                member = next(n for n in zf.namelist()
                              if n.endswith(name))
                return zf.read(member).decode("latin1").splitlines()

            users = {}
            for ln in read("users.dat"):
                uid, gender, age, job, _zip = ln.split("::")
                users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                   int(job))
            movies = {}
            for ln in read("movies.dat"):
                mid, title, genres = ln.split("::")
                movies[int(mid)] = (title, genres.split("|"))
            rows = []
            for ln in read("ratings.dat"):
                uid, mid, rating, _ts = ln.split("::")
                uid, mid = int(uid), int(mid)
                if uid in users and mid in movies:
                    rows.append((uid, *users[uid], mid, float(rating)))
        rng = np.random.RandomState(rand_seed)
        mask = rng.rand(len(rows)) < test_ratio
        self.rows = [r for r, m in zip(rows, mask)
                     if (m if mode == "test" else not m)]
        self.movie_info = movies
        self.user_info = users

    def __getitem__(self, i):
        uid, gender, age, job, mid, rating = self.rows[i]
        return (np.asarray([uid]), np.asarray([gender]), np.asarray([age]),
                np.asarray([job]), np.asarray([mid]),
                np.asarray([rating], np.float32))

    def __len__(self):
        return len(self.rows)


class _WMTBase(Dataset):
    """Shared tab-separated parallel-corpus parsing for WMT14/WMT16
    (reference preprocessed archives: one 'src\\ttgt' pair per line)."""

    BOS, EOS, UNK = "<s>", "<e>", "<unk>"

    def _build(self, lines, src_dict_size, trg_dict_size=None):
        import collections

        trg_dict_size = trg_dict_size if trg_dict_size is not None \
            else src_dict_size
        src_freq = collections.Counter()
        trg_freq = collections.Counter()
        pairs = []
        for ln in lines:
            if "\t" not in ln:
                continue
            s, t = ln.split("\t", 1)
            sw, tw = s.split(), t.split()
            pairs.append((sw, tw))
            src_freq.update(sw)
            trg_freq.update(tw)

        def make_dict(freq, size):
            words = [w for w, _ in freq.most_common(max(size - 3, 0))]
            d = {self.BOS: 0, self.EOS: 1, self.UNK: 2}
            for w in words:
                d[w] = len(d)
            return d

        self.src_dict = make_dict(src_freq, src_dict_size)
        self.trg_dict = make_dict(trg_freq, trg_dict_size)
        unk = 2
        self.data = []
        for sw, tw in pairs:
            src_ids = [self.src_dict.get(w, unk) for w in sw]
            trg_ids = [self.trg_dict.get(w, unk) for w in tw]
            self.data.append((
                np.asarray(src_ids, np.int64),
                np.asarray([0] + trg_ids, np.int64),
                np.asarray(trg_ids + [1], np.int64)))

    def __getitem__(self, i):
        return self.data[i]

    def __len__(self):
        return len(self.data)


class WMT14(_WMTBase):
    """text/datasets/wmt14.py parity (preprocessed en→fr pairs)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        import tarfile

        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/wmt14/wmt14.tgz")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="WMT14", path=path))
        want = {"train": "train/", "test": "test/", "gen": "gen/"}[mode]
        lines = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if want in m.name and m.isfile():
                    lines += tf.extractfile(m).read().decode(
                        "utf-8", "ignore").splitlines()
        self._build(lines, dict_size)


class WMT16(_WMTBase):
    """text/datasets/wmt16.py parity (en↔de multi30k-style archive)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        import tarfile

        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/wmt16/wmt16.tar.gz")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="WMT16", path=path))
        fname = {"train": "train", "test": "test", "val": "val"}[mode]
        lines = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if m.isfile() and m.name.rstrip("/").endswith(fname):
                    lines += tf.extractfile(m).read().decode(
                        "utf-8", "ignore").splitlines()
        self._build(lines, src_dict_size, trg_dict_size)


class Conll05st(Dataset):
    """text/datasets/conll05.py parity: SRL test set (wsj words + props
    column files inside conll05st-tests.tar.gz)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=True):
        import gzip
        import tarfile

        path = data_file or os.path.expanduser(
            "~/.cache/paddle/dataset/conll05st/conll05st-tests.tar.gz")
        if not os.path.exists(path):
            raise RuntimeError(_NO_EGRESS.format(name="Conll05st", path=path))
        with tarfile.open(path) as tf:
            def read_gz(suffix):
                m = next(mm for mm in tf.getmembers()
                         if mm.name.endswith(suffix))
                return gzip.decompress(tf.extractfile(m).read()) \
                    .decode().splitlines()

            words_lines = read_gz("words.gz")
            props_lines = read_gz("props.gz")
        # sentences separated by blank lines; props columns: verb + tags
        self.sentences = []
        cur_w, cur_p = [], []
        for wl, pl in zip(words_lines, props_lines):
            if not wl.strip():
                if cur_w:
                    self.sentences.append((cur_w, cur_p))
                cur_w, cur_p = [], []
                continue
            cur_w.append(wl.strip())
            cur_p.append(pl.split())
        if cur_w:
            self.sentences.append((cur_w, cur_p))
        # flatten: one sample per predicate per sentence (SRL convention)
        self.data = []
        vocab = {}
        for words, props in self.sentences:
            for w in words:
                vocab.setdefault(w.lower(), len(vocab))
            n_preds = len(props[0]) - 1 if props and props[0] else 0
            for k in range(n_preds):
                labels = self._decode_props([p[k + 1] for p in props])
                verb = next((w for w, p in zip(words, props)
                             if p[0] != "-"), "-")
                ids = np.asarray([vocab[w.lower()] for w in words], np.int64)
                self.data.append((ids, verb, labels))
        self.word_dict = vocab

    @staticmethod
    def _decode_props(col):
        """IOB decode of the bracketed (A0* ... *) proposition column."""
        labels = []
        current = None
        for tok in col:
            if tok.startswith("("):
                current = tok.strip("()*")
                labels.append("B-" + current)
                if tok.endswith(")"):
                    current = None
            elif current is not None:
                labels.append("I-" + current)
                if tok.endswith(")"):
                    current = None
            else:
                labels.append("O")
        return labels

    def __getitem__(self, i):
        return self.data[i]

    def __len__(self):
        return len(self.data)
