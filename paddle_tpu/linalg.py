"""paddle.linalg namespace (python/paddle/linalg.py re-export pattern):
the linear-algebra surface lives in ops/linalg.py; this module mirrors the
reference's public module layout."""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, diag_embed, diagonal,
    eig, eigh, eigvals, eigvalsh, householder_product, inverse, inverse as inv, kron,
    lstsq, lu, lu_unpack, matmul, matrix_norm, matrix_power, matrix_rank,
    multi_dot, norm, pinv, qr, slogdet, solve, svd, svdvals,
    triangular_solve, vector_norm)

from .ops import schema as _schema  # noqa: E402

ormqr = _schema.generated("ormqr")
cholesky_inverse = _schema.generated("cholesky_inverse")
svd_lowrank = _schema.generated("svd_lowrank")
pca_lowrank = _schema.generated("pca_lowrank")
cdist = _schema.generated("cdist")


def matrix_transpose(x, name=None):
    """paddle.linalg.matrix_transpose: swap the last two axes."""
    from .ops.manipulation import swapaxes

    return swapaxes(x, -1, -2)


def matrix_exp(x, name=None):
    """paddle.linalg.matrix_exp via jax.scipy.linalg.expm."""
    from .ops.registry import apply
    import jax.scipy.linalg as _jsl

    return apply("matrix_exp", lambda a: _jsl.expm(a), x)
