"""paddle.linalg namespace (python/paddle/linalg.py re-export pattern):
the linear-algebra surface lives in ops/linalg.py; this module mirrors the
reference's public module layout."""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, diag_embed, diagonal,
    eig, eigh, eigvals, eigvalsh, householder_product, inverse, inverse as inv, kron,
    lstsq, lu, lu_unpack, matmul, matrix_norm, matrix_power, matrix_rank,
    multi_dot, norm, pinv, qr, slogdet, solve, svd, svdvals,
    triangular_solve, vector_norm)

from .ops import schema as _schema  # noqa: E402

ormqr = _schema.generated("ormqr")
cholesky_inverse = _schema.generated("cholesky_inverse")
svd_lowrank = _schema.generated("svd_lowrank")
pca_lowrank = _schema.generated("pca_lowrank")
cdist = _schema.generated("cdist")


def matrix_transpose(x, name=None):
    """paddle.linalg.matrix_transpose: swap the last two axes."""
    from .ops.manipulation import swapaxes

    return swapaxes(x, -1, -2)


def matrix_exp(x, name=None):
    """paddle.linalg.matrix_exp via jax.scipy.linalg.expm."""
    from .ops.registry import apply
    import jax.scipy.linalg as _jsl

    return apply("matrix_exp", lambda a: _jsl.expm(a), x)


def fp8_fp8_half_gemm_fused(x, y, transpose_x=False, transpose_y=False,
                            bias=None, scale=1.0, output_dtype="float16",
                            act="identity", name=None):
    """paddle.linalg.fp8_fp8_half_gemm_fused parity
    (python/paddle/tensor/linalg.py:357 over the cutlass fp8 GEMM): both
    operands quantize to float8_e4m3, the product accumulates at higher
    precision, ``scale`` rescales, bias + activation fuse, and the result
    lands in float16/bfloat16.

    TPU-native: jnp.matmul over jnp.float8_e4m3fn inputs with a f32
    ``preferred_element_type`` — XLA lowers to native fp8 MXU paths on
    hardware that has them and upcasts elsewhere; either way the VALUES
    carry fp8 quantization exactly like the reference kernel's.
    """
    import jax
    import jax.numpy as jnp

    from .ops.registry import apply

    if output_dtype not in ("float16", "bfloat16"):
        raise ValueError(
            f"output_dtype must be float16 or bfloat16, got {output_dtype!r}")
    if act not in ("identity", "relu", "gelu"):
        raise ValueError(f"act must be identity/relu/gelu, got {act!r}")
    out_dt = jnp.dtype(output_dtype)

    def fn(a, b, *rest):
        a8 = a.astype(jnp.float8_e4m3fn)
        b8 = b.astype(jnp.float8_e4m3fn)
        if transpose_x:
            a8 = jnp.swapaxes(a8, -1, -2)
        if transpose_y:
            b8 = jnp.swapaxes(b8, -1, -2)
        out = jnp.matmul(a8, b8, preferred_element_type=jnp.float32) * scale
        if rest:
            out = out + rest[0].astype(jnp.float32)
        if act == "relu":
            out = jax.nn.relu(out)
        elif act == "gelu":
            out = jax.nn.gelu(out, approximate=False)
        return out.astype(out_dt)

    args = (x, y) if bias is None else (x, y, bias)
    return apply("fp8_fp8_half_gemm_fused", fn, *args, differentiable=False)
