"""paddle.linalg namespace (python/paddle/linalg.py re-export pattern):
the linear-algebra surface lives in ops/linalg.py; this module mirrors the
reference's public module layout."""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, diag_embed, diagonal,
    eig, eigh, eigvals, eigvalsh, householder_product, inverse as inv, kron,
    lstsq, lu, lu_unpack, matmul, matrix_norm, matrix_power, matrix_rank,
    multi_dot, norm, pinv, qr, slogdet, solve, svd, svdvals,
    triangular_solve, vector_norm)
