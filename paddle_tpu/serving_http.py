"""OpenAI-style HTTP front-end over the continuous-batching engine.

Deployment-surface parity: the reference ships its serving engine behind
an HTTP deployment story (FastDeploy / Paddle Serving around the
`block_multi_head_attention` runtime); this is the equivalent front door
for paddle_tpu, stdlib-only (no web framework in the image):

- ``POST /v1/completions`` — OpenAI completions shape: ``prompt`` (string,
  needs a ``tokenizer``) or ``prompt_token_ids`` (list of ints, no
  tokenizer needed), ``max_tokens``, ``temperature`` / ``top_k`` /
  ``top_p`` (per-request sampling rides the engine's per-row program),
  ``stop_token_ids``, ``logprobs``, ``n`` (sampled sibling completions
  batch in-flight on the engine), ``stream`` (SSE chunks per token,
  ``data: [DONE]`` terminator), ``priority`` / ``slo_ms`` (SLO-aware
  admission — docs/SERVING.md "Scheduling & SLOs"), and ``pixel_values``
  ([n_images, C, H, W] nested lists) for multimodal models — image and
  text requests batch in-flight. A bounded engine queue (``max_queue``)
  answers ``429 Too Many Requests`` + ``Retry-After`` when full;
- ``GET /v1/models`` and ``GET /health``;
- ``GET /metrics`` — Prometheus text exposition of the process-wide
  registry (``paddle_tpu.observability``): latency histograms
  (queue-wait, TTFT, inter-token, prefill, decode-step), request/token
  counters, occupancy gauges. Scrape it next to /health.
- ``GET /trace?rid=N`` (or ``?trace_id=...``) — the request's recorded
  spans as JSON, and ``GET /trace/chrome`` — a chrome://tracing JSON
  download (optionally filtered the same way; the full dump merges the
  profiler's host events onto the same timeline). ``POST
  /v1/completions`` accepts an inbound W3C ``traceparent`` header
  (continuing the caller's trace) and always answers with one, so
  external callers correlate their spans with the engine's.
- ``GET /debug/dump`` — the incident bundle (flight-recorder event
  ring, spans, metrics snapshot, engine slot/queue state, thread
  stacks) as JSON on demand; ``?write=1`` persists it rank-suffixed to
  the incident directory. ``GET /debug/events?since=N`` tails the
  flight-recorder ring incrementally. See docs/SERVING.md "Incident
  forensics".
- ``GET /audit`` — the correctness sentinel's state (verdict counts,
  skip reasons, canary fingerprint, recent verdicts, sealed divergence
  bundles). ``POST /v1/completions`` accepts an ``X-Audit: 1`` header
  or body ``audit=true`` for a GUARANTEED shadow audit whose verdict
  block rides the response next to ``usage``; sampled shadow audits
  and pinned canary probes run on the named audit-worker thread. See
  docs/SERVING.md "Correctness sentinel".

Single-engine-thread design: device state (page pool, slot buffers) is
touched ONLY by the engine thread; HTTP handler threads enqueue
submissions and wait on per-request queues fed by the engine's
``on_token`` streaming callbacks. The engine thread interleaves admission
and decode exactly like ``run_until_done`` — in-flight batching across
concurrent HTTP clients is the whole point.

The handler skeleton (:class:`ServingHandlerBase`: observability GETs,
traceparent echo, chunked SSE plumbing, POST span wiring) is shared with
the disaggregated tier's :class:`~paddle_tpu.serving_cluster.RouterServer`
and role workers — one front-door surface, however many processes serve
behind it.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

import numpy as np

from .observability import PROMETHEUS_CONTENT_TYPE, get_registry
from .observability import flightrecorder as _frec
from .observability import kvatlas as _kvatlas
from .observability import perf as _perf
from .observability import sentinel as _sentinel
from .observability import tracing as _tracing
from .observability.catalog import HTTP_REQUESTS
from .serving import DeadlineExceeded, QueueFull

__all__ = ["CompletionServer", "ServingHandlerBase", "serve",
           "DEADLINE_HEADER", "AUDIT_HEADER", "timeseries_payload",
           "alerts_payload", "profile_payload", "kvstate_payload"]

#: end-to-end deadline propagation: the cluster router stamps each
#: upstream hop with the request's REMAINING budget in milliseconds, so
#: the worker's admission deadline is the router's minus elapsed time —
#: never a second, fresh budget. A non-positive value answers 504
#: (code=deadline_exceeded) before the engine is touched.
DEADLINE_HEADER = "X-Request-Deadline"

# known routes for the http counter — anything else buckets under
# "other" so a scanner can't explode the label cardinality
_KNOWN_ROUTES = ("/health", "/metrics", "/metrics/cluster", "/v1/models",
                 "/v1/completions", "/v1/prefill", "/trace",
                 "/trace/chrome", "/debug/dump", "/debug/events",
                 "/timeseries", "/alerts", "/profile", "/profile/cluster",
                 "/kvstate", "/kvstate/cluster", "/audit", "/audit/cluster")

#: ``X-Audit: 1`` on a completions POST forces a shadow audit of that
#: request (the on-demand contract): the response's ``audit`` block
#: carries the verdict. Equivalent to body ``audit=true``.
AUDIT_HEADER = "X-Audit"


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def timeseries_payload(query: str) -> dict:
    """``GET /timeseries`` body: the process store's pinned-schema dump
    (optionally ``?metric=``-filtered and ``?window=``-bounded seconds)
    plus the store's own stats — the sparkline feed for
    scripts/watch_cluster.py."""
    from .observability import timeseries as _ts

    store = _ts.get_store()
    q = parse_qs(query)
    window = None
    if q.get("window"):
        try:
            window = float(q["window"][0])
        except ValueError:
            window = None
    metric = (q.get("metric") or [None])[0]
    payload = store.dump(window_s=window, name=metric)
    payload["stats"] = store.stats()
    return payload


def profile_payload(query: str = "") -> dict:
    """``GET /profile`` body: every registered engine's step anatomy —
    per-phase p50/p99/share over the recent window, roofline ratios and
    MFU, and the top-K slowest steps with their flight-recorder seqs
    (``?top=`` bounds K; docs/SERVING.md 'Step anatomy & roofline
    accounting')."""
    q = parse_qs(query)
    top_k = 5
    if q.get("top"):
        try:
            top_k = max(0, min(int(q["top"][0]), 64))
        except ValueError:
            top_k = 5
    return _perf.profile_payload(top_k)


def kvstate_payload(query: str = "") -> dict:
    """``GET /kvstate`` body: every registered engine's KV & memory
    atlas — pool occupancy/headroom, the per-slot page ledger, the
    prefix-reuse index, host-parked preemption bytes, the
    measured-vs-preflight capacity join, and the time-to-full forecast
    (docs/SERVING.md 'KV & memory atlas')."""
    del query  # no parameters yet; signature matches the payload peers
    return _kvatlas.kvstate_payload()


def alerts_payload(manager) -> dict:
    """``GET /alerts`` body for one AlertManager (None renders the
    disabled shape — same keys, so pollers never branch)."""
    if manager is None:
        return {"enabled": False, "manager": None, "firing": [],
                "alerts": [], "transitions": [], "transitions_total": 0}
    payload = manager.state()
    payload["enabled"] = True
    return payload


class _Submission:
    __slots__ = ("ids", "params", "events", "rid", "n", "rids",
                 "trace_ctx", "handoff")

    def __init__(self, ids, params, n=1, trace_ctx=None, handoff=None):
        self.ids = ids
        self.params = params
        self.events: "queue.Queue" = queue.Queue()
        self.rid = None
        self.n = n          # OpenAI "n": sibling completions of one prompt
        self.rids = []
        self.trace_ctx = trace_ctx  # (trace_id, parent_span_id) | None
        self.handoff = handoff  # prefilled-KV bundle (disaggregated tier)


def _deadline_response(miss_note: str = "") -> dict:
    """The ONE body shape every deadline 504 answers with: ``code`` is
    how the cluster router tells a deadline-504 (terminal — forward
    verbatim, the budget is global) from a transport/handoff 504
    (retryable on another worker)."""
    return {"error": "request deadline exceeded" + miss_note,
            "code": "deadline_exceeded"}


def apply_deadline_header(handler, params) -> Optional[tuple]:
    """Fold an inbound X-Request-Deadline header (remaining budget, ms)
    into the request params: the header WINS over any body ``slo_ms``
    because it already accounts for time spent upstream. Returns a
    ``(status, body)`` error response when the header is malformed or
    the budget is already spent, else None."""
    hdr = handler.headers.get(DEADLINE_HEADER)
    if hdr is None:
        return None
    try:
        remaining_ms = float(hdr)
    except (TypeError, ValueError):
        return (400, {"error": f"invalid {DEADLINE_HEADER} header "
                               f"{hdr!r}: want remaining budget in ms"})
    if remaining_ms <= 0:
        return (504, _deadline_response(
            f" (budget spent {-remaining_ms:.0f}ms before admission)"))
    params["slo_ms"] = remaining_ms
    return None


class _Cancel:
    """Engine-thread command: cancel every engine request of a
    submission (a streaming client disconnected). Queued AFTER the
    submission it refers to, so by the time the engine thread sees it
    the rids are assigned (FIFO) — and cancel() ends the request's root
    span with status=cancelled."""

    __slots__ = ("sub",)

    def __init__(self, sub: _Submission):
        self.sub = sub


class EngineCommand:
    """A unit of work executed ON the engine thread (the only device-state
    toucher), with its result posted back to the waiting handler thread —
    how the cluster worker runs prefill exports without a second thread
    ever touching the page pool. Subclasses implement ``execute``."""

    def __init__(self):
        self.events: "queue.Queue" = queue.Queue()

    def execute(self, engine):
        raise NotImplementedError


class ServingHandlerBase(BaseHTTPRequestHandler):
    """The shared front-door handler skeleton: observability GET routes
    (/health, /metrics, /trace, /trace/chrome, /debug/*), W3C traceparent
    parse/echo around POSTs, the http counter, and chunked-SSE plumbing.

    Concrete servers subclass per instance (``class Handler(
    ServingHandlerBase): server_obj = self``) and customize through the
    ``server_obj`` hooks: ``_refresh_metrics`` / ``_health_payload`` /
    ``_models_payload`` / ``_post_handler`` / ``_extra_get`` — the
    CompletionServer serves an engine behind them, the cluster
    RouterServer a whole worker pool."""

    protocol_version = "HTTP/1.1"
    server_obj = None           # the owning server (set by the factory)
    known_routes = _KNOWN_ROUTES
    post_span_name = None       # default: http.request

    # the handler's POST span (None on GETs / when tracing is off);
    # responses echo its traceparent
    _trace_span = None

    def log_message(self, *a):  # silence request logging
        pass

    # ---- small shared plumbing ----------------------------------------
    def _count(self, code):
        route = urlsplit(self.path).path
        if route not in self.known_routes:
            route = "other"
        HTTP_REQUESTS.inc(path=route, code=str(code))

    def _send_traceparent(self):
        sp = self._trace_span
        if sp is not None and sp.trace_id:
            self.send_header(
                _tracing.TRACEPARENT_HEADER,
                _tracing.format_traceparent(sp.trace_id, sp.span_id))

    def _json(self, code, obj, headers=()):
        self._count(code)
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self._send_traceparent()
        self.end_headers()
        self.wfile.write(body)

    def _chunk(self, payload: bytes):
        """One HTTP/1.1 chunked-encoding frame (the SSE write primitive)."""
        self.wfile.write(f"{len(payload):X}\r\n".encode()
                         + payload + b"\r\n")

    def _begin_sse(self):
        """Status + SSE headers for a streaming response; after this only
        ``_chunk`` writes are legal on the connection."""
        self._count(200)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self._send_traceparent()
        self.end_headers()

    def _trace_query(self, query):
        """?trace_id=... | ?rid=N[&engine=...] -> trace_id or
        None (unknown rid / malformed query)."""
        q = parse_qs(query)
        if q.get("trace_id"):
            return q["trace_id"][0]
        if q.get("rid"):
            try:
                rid = int(q["rid"][0])
            except ValueError:
                return None
            engine = (q.get("engine") or [None])[0]
            return self.server_obj._tracer.find_request_trace(
                rid, engine=engine)
        return None

    # ---- GET -----------------------------------------------------------
    def do_GET(self):
        # one handler instance serves a whole keep-alive
        # connection: drop any previous POST's span so GETs
        # don't echo a stale traceparent
        self._trace_span = None
        route, _, query = self.path.partition("?")
        if self._common_get(route, query):
            return
        if self.server_obj._extra_get(self, route, query):
            return
        self._json(404, {"error": "not found"})

    def _common_get(self, route, query) -> bool:
        srv = self.server_obj
        if route == "/trace":
            tid = self._trace_query(query)
            if tid is None:
                self._json(404, {
                    "error": "no trace: pass ?rid=<request id> "
                             "(finished or in flight) or "
                             "?trace_id=<32-hex id>"})
                return True
            # include_live: the POST handler's span ends only after its
            # response bytes hit the socket, so a caller chaining POST ->
            # GET /trace would otherwise race the handler thread and see
            # a tree missing its http.request node
            self._json(200, {
                "trace_id": tid,
                "spans": srv._tracer.spans(tid, include_live=True)})
            return True
        if route == "/trace/chrome":
            # chrome://tracing download; unfiltered dumps merge
            # the profiler's host events onto the same timeline
            tid = self._trace_query(query) if query else None
            if query and tid is None:
                self._json(404, {"error": "no such trace"})
                return True
            trace = srv._tracer.export_chrome(trace_id=tid)
            self._json(200, trace, headers=(
                ("Content-Disposition",
                 'attachment; filename="paddle_tpu_trace.json"'),))
            return True
        if route == "/debug/dump":
            # the incident bundle ON DEMAND (no crash needed):
            # event ring, spans, metrics, engine slot/queue
            # state, config, thread stacks. ?write=1 persists it
            # to the reporter's incident directory instead.
            rep = _frec.get_reporter()
            if parse_qs(query).get("write"):
                path = rep.dump("manual",
                                context="GET /debug/dump?write=1")
                self._json(200, {"path": path})
                return True
            _frec.RECORDER.record(_frec.EV_INCIDENT,
                                  reason="manual", path=None)
            self._json(200, rep.bundle("manual", context="GET /debug/dump"))
            return True
        if route == "/debug/events":
            q = parse_qs(query)
            try:
                since = int((q.get("since") or ["0"])[0])
                limit = int((q.get("limit") or ["500"])[0])
            except ValueError:
                self._json(400, {"error": "since/limit must be integers"})
                return True
            kind = (q.get("kind") or [None])[0]
            rec = _frec.get_recorder()
            evs = rec.events(since=since, kind=kind, limit=limit)
            self._json(200, {
                "events": evs,
                # resume cursor: pass back as ?since= to tail the
                # ring incrementally
                "next_since": (evs[-1]["seq"] if evs else since),
                "stats": rec.stats(),
            })
            return True
        if route == "/metrics":
            # refresh the occupancy gauges off ONE stats() snapshot,
            # then render the whole registry; counted BEFORE the render
            # so a scrape sees itself
            srv._refresh_metrics()
            self._count(200)
            body = get_registry().render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return True
        if route == "/timeseries":
            # the TSDB window dump: history for sparklines/debugging,
            # where /metrics is the point-in-time exposition
            self._json(200, srv._timeseries_payload(query))
            return True
        if route == "/alerts":
            self._json(200, srv._alerts_payload())
            return True
        if route == "/health":
            self._json(200, srv._health_payload())
            return True
        if route == "/v1/models":
            self._json(200, srv._models_payload())
            return True
        return False

    # ---- POST ----------------------------------------------------------
    def do_POST(self):
        # one span per POST (http.request here; router.request on the
        # cluster router), continuing the caller's trace when an inbound
        # W3C traceparent header is present; its context parents the
        # engine's serving.request root span
        rec = _frec.RECORDER
        if rec.enabled:
            rec.record(_frec.EV_HTTP_REQUEST, method="POST",
                       path=self.path)
        ctx = _tracing.parse_traceparent(
            self.headers.get(_tracing.TRACEPARENT_HEADER))
        sp = self.server_obj._tracer.start_span(
            self.post_span_name or _tracing.SPAN_HTTP_REQUEST,
            trace_id=ctx[0] if ctx else None,
            parent_id=ctx[1] if ctx else None,
            attrs={"method": "POST", "path": self.path})
        self._trace_span = sp if sp else None
        try:
            self._post_inner()
        except BaseException:
            sp.end("error")
            raise
        sp.end()

    def _post_inner(self):
        # drain the body FIRST: replying without reading it would
        # desync a keep-alive connection (HTTP/1.1 is on), making
        # the next request parse the unread bytes as a request line
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
        except Exception:
            return self._json(400, {"error": "unreadable body"})
        route = urlsplit(self.path).path
        fn = self.server_obj._post_handler(route)
        if fn is None:
            return self._json(404, {"error": "not found"})
        try:
            req = json.loads(body or b"{}")
        except Exception:
            return self._json(400, {"error": "invalid JSON body"})
        return fn(self, req)


class CompletionServer:
    """HTTP wrapper around one ContinuousBatchEngine.

    ``tokenizer`` is optional and duck-typed (``encode(str) -> ids``,
    ``decode(ids) -> str`` — a transformers tokenizer works); without one
    the server speaks token ids (``prompt_token_ids`` in,
    ``token_ids`` out).
    """

    def __init__(self, engine, tokenizer=None, model_name: str = "paddle-tpu",
                 host: str = "127.0.0.1", port: int = 0,
                 enable_tracing: bool = True,
                 enable_flight_recorder: bool = True,
                 enable_timeseries: bool = True,
                 ts_interval_s: Optional[float] = None,
                 audit_rate: Optional[float] = None,
                 canary_interval_s: Optional[float] = None,
                 divergence_dir: Optional[str] = None):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        # the server IS a tracing subscriber (it serves /trace), so it
        # enables the process-wide tracer; pass enable_tracing=False to
        # keep the engine's guarded no-trace fast path
        if enable_tracing:
            _tracing.get_tracer().enable()
        self._tracer = _tracing.get_tracer()
        # likewise a flight-recorder subscriber (it serves /debug/*):
        # turn the black box on and let incident bundles see this
        # engine's slot/queue state
        if enable_flight_recorder:
            _frec.get_recorder().enable()
        # and a time-series subscriber (it serves /timeseries + /alerts):
        # start the process-wide ts-sampler and attach the default
        # SLO/burn-rate AlertManager — both process singletons, shared
        # by every server in the process like the tracer/recorder
        self._alert_mgr = None
        if enable_timeseries:
            from .observability import alerts as _alerts
            from .observability import timeseries as _ts

            _ts.get_store().start(interval_s=ts_interval_s)
            self._alert_mgr = _alerts.default_manager()
        _frec.get_reporter().register_engine(
            getattr(engine, "_engine_label", "engine"), engine)
        # and a step-anatomy subscriber (it serves /profile): enable the
        # engine's profiler — the guarded fast path only pays once a
        # subscriber exists, exactly like the tracer/recorder
        prof = getattr(engine, "profiler", None)
        if prof is not None:
            prof.enable()
        # the server also serves /kvstate: the KV & memory atlas gets a
        # subscriber the moment an HTTP front-end wraps the engine
        atlas = getattr(engine, "kvatlas", None)
        if atlas is not None:
            atlas.enable()
        # and /audit: the correctness sentinel wakes with the front-end.
        # audit_rate=0.0 (the default) still serves the on-demand
        # X-Audit contract — only SAMPLED shadow audits are off; the env
        # knobs let the cluster launcher arm sampling/canaries without
        # plumbing kwargs through every process entry
        self._sentinel = getattr(engine, "sentinel", None)
        if self._sentinel is not None:
            if audit_rate is None:
                audit_rate = _env_float("PDTPU_AUDIT_RATE")
            if canary_interval_s is None:
                canary_interval_s = _env_float("PDTPU_CANARY_INTERVAL_S")
            if divergence_dir is None:
                divergence_dir = os.environ.get("PDTPU_DIVERGENCE_DIR")
            self._sentinel.enable(audit_rate=audit_rate,
                                  canary_interval_s=canary_interval_s,
                                  divergence_dir=divergence_dir)
            self._sentinel.submitter = self._canary_submit
            self._sentinel.start()
        self._subs: "queue.Queue[_Submission]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._engine_loop,  # pdlint: disable=error-thread-escape -- deliberate crash boundary: incident_scope writes the forensics bundle and the death is VISIBLE (waiters time out against _stop, /health degrades)
                                        daemon=True, name="engine-loop")
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="http-loop")

    # ---- lifecycle ----------------------------------------------------
    @property
    def address(self):
        return self._httpd.server_address  # (host, port) — port resolved

    def start(self):
        self._thread.start()
        self._http_thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._sentinel is not None:
            # stop the audit worker FIRST: a canary submitted after the
            # engine loop exits would wait out its full timeout
            self._sentinel.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=30)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ---- engine thread -------------------------------------------------
    def submit_command(self, cmd: EngineCommand, timeout: float = 120.0):
        """Run ``cmd`` on the engine thread and wait for its result;
        raises the error classes the POST paths map to 400/500."""
        self._subs.put(cmd)
        while True:
            try:
                kind, payload, _ = cmd.events.get(timeout=1.0)
            except queue.Empty:
                timeout -= 1.0
                if self._stop.is_set():
                    raise RuntimeError("engine stopped")
                if timeout <= 0:
                    raise TimeoutError("engine command timed out")
                continue
            if kind == "error":
                raise ValueError(payload)
            if kind == "fault":
                raise RuntimeError(payload)
            return payload

    def _handle_submission(self, sub):
        """Process one queue item ON the engine thread: a cancel command
        frees its submission's slots; an EngineCommand executes and posts
        its result; a submission becomes engine requests (add_request
        allocates host-side, admission happens inside step())."""
        eng = self.engine
        if isinstance(sub, _Cancel):
            for rid in sub.sub.rids:
                try:
                    eng.cancel(rid)
                except Exception:
                    # cancel() refills the freed slot (_admit): a failed
                    # refill must stop the loop like a failed step —
                    # waiting handlers time out against _stop, not hang
                    self._stop.set()
                    raise
            return
        if isinstance(sub, EngineCommand):
            try:
                sub.events.put(("ok", sub.execute(eng), True))
            except (ValueError, TypeError, NotImplementedError) as e:
                sub.events.put(("error", str(e), True))
            except Exception as e:    # engine fault -> HTTP 500
                sub.events.put(("fault", str(e), True))
            return
        ev = sub.events

        def on_token(rid, tok, done, logprob, _ev=ev):
            _ev.put(("token", (rid, tok, logprob), done))

        def on_shed(rid, info, _ev=ev):
            # the engine dropped a QUEUED request (deadline expired /
            # displaced at capacity): a typed event, so the waiting
            # handler answers 504/429 instead of stalling silently
            _ev.put(("shed", info, True))

        try:
            if sub.handoff is not None:
                if sub.handoff.get("kind") == "migrate":
                    # live migration: the bundle carries the decode-side
                    # request state (sampling, stops, budget) — admission
                    # takes no params, the stream resumes mid-decode
                    sub.rids.append(
                        eng.admit_migrated(sub.handoff, on_token=on_token,
                                           trace_ctx=sub.trace_ctx,
                                           on_shed=on_shed))
                else:
                    # disaggregated tier: the prompt's KV arrived from a
                    # prefill worker; admit it without a local prefill
                    sub.rids.append(
                        eng.admit_prefilled(sub.handoff, on_token=on_token,
                                            trace_ctx=sub.trace_ctx,
                                            on_shed=on_shed,
                                            **sub.params))
            else:
                for _ in range(sub.n):
                    sub.rids.append(
                        eng.add_request(sub.ids, on_token=on_token,
                                        trace_ctx=sub.trace_ctx,
                                        on_shed=on_shed,
                                        **sub.params))
            sub.rid = sub.rids[0]
        except DeadlineExceeded as e:
            # the budget was spent before submission (a deadline header
            # that expired in transit): typed 504, siblings cancelled
            for rid in sub.rids:
                eng.cancel(rid)
            ev.put(("shed", {"where": "expired", "error": str(e),
                             "miss_ms": e.miss_ms}, True))
        except QueueFull as e:
            # bounded admission queue -> HTTP 429 + Retry-After; siblings
            # of an n>1 request admitted before the bound hit are
            # cancelled (the client sees ONE atomic rejection)
            for rid in sub.rids:
                eng.cancel(rid)
            ev.put(("busy", {"error": str(e),
                             "retry_after": max(1, round(e.retry_after_s))},
                    True))
        except (ValueError, TypeError, NotImplementedError) as e:
            # client error (bad params, pixel_values to a
            # non-multimodal model, ...) -> HTTP 400
            ev.put(("error", str(e), True))
        except Exception as e:      # engine fault -> HTTP 500
            ev.put(("fault", str(e), True))

    def _engine_loop(self):
        # crash boundary: an escaping engine fault writes an incident
        # bundle (when a reporter is active) before the thread dies, and
        # an XLA RESOURCE_EXHAUSTED re-raises enriched with the bundle
        # path — the operator gets forensics, not a bare traceback
        with _frec.incident_scope("serving.engine_loop"):
            self._engine_loop_inner()

    def _engine_loop_inner(self):
        eng = self.engine
        while not self._stop.is_set():
            # drain submissions (engine thread is the ONLY device-state
            # toucher)
            drained = False
            while True:
                try:
                    sub = self._subs.get_nowait()
                except queue.Empty:
                    break
                drained = True
                self._handle_submission(sub)
            if (eng.num_active or getattr(eng, "_queue", None)
                    or getattr(eng, "_chunking", None)):
                try:
                    eng.step()
                except Exception:
                    # a failed step (poisoned engine, device fault) must
                    # not hang clients: stop the loop; waiting handlers
                    # time out against _stop and answer 500
                    self._stop.set()
                    raise
            elif not drained:
                # idle: block briefly, then handle the submission
                # DIRECTLY — re-enqueueing at the tail would let a
                # steady trickle of newer submissions starve it
                try:
                    self._handle_submission(self._subs.get(timeout=0.05))
                except queue.Empty:
                    pass

    # ---- handler hooks --------------------------------------------------
    def _make_handler(server_self):
        class Handler(ServingHandlerBase):
            server_obj = server_self

        return Handler

    def _refresh_metrics(self):
        # one stats() snapshot refreshes the occupancy gauges
        self.engine.stats()

    def _health_payload(self) -> dict:
        eng = self.engine
        stats = eng.stats()
        # legacy top-level keys alias the SAME stats read (one
        # snapshot — a monitor must never see them disagree)
        payload = {
            "status": "ok",
            "active": stats["requests_active"],
            "queued": stats["requests_queued"],
            "max_batch": eng.max_batch,
            # the LIVE admission budget — < max_batch after an OOM
            # degrade (sched.degrade), so a balancer sees the reduced
            # capacity directly on /health
            "max_active_slots": stats.get("max_active_slots",
                                          eng.max_batch),
            "max_len": eng.max_len,
            "stats": stats,
        }
        payload.update(self.health_extra())
        return payload

    def health_extra(self) -> dict:
        """Extra /health keys (cluster workers add role / replica_id /
        lease age here)."""
        return {}

    def _models_payload(self) -> dict:
        return {
            "object": "list",
            "data": [{"id": self.model_name, "object": "model"}],
        }

    def _timeseries_payload(self, query: str) -> dict:
        return timeseries_payload(query)

    def _alerts_payload(self) -> dict:
        return alerts_payload(self._alert_mgr)

    def _extra_get(self, handler, route, query) -> bool:
        if route == "/profile":
            handler._json(200, profile_payload(query))
            return True
        if route == "/kvstate":
            handler._json(200, kvstate_payload(query))
            return True
        if route == "/audit":
            handler._json(200, _sentinel.audit_payload())
            return True
        return False

    def _canary_submit(self, ids, max_new):
        """Sentinel-injected canary runner (audit-worker thread): the
        pinned prompt rides the REAL submission path — engine thread,
        live decode, every feature under test — with its own audit off;
        the sentinel compares against the pinned baseline itself.
        Returns (tokens, logprobs), or None when the engine can't take
        it right now (canaries only ever spend idle capacity)."""
        if self._stop.is_set():
            return None
        sub = _Submission([int(t) for t in np.asarray(ids).reshape(-1)],
                          dict(max_new_tokens=int(max_new), audit=False,
                               logprobs=True))
        self._subs.put(sub)
        toks, lps = [], []
        deadline = time.time() + 60.0
        while True:
            try:
                kind, payload, done = sub.events.get(timeout=1.0)
            except queue.Empty:
                if self._stop.is_set() or time.time() > deadline:
                    return None
                continue
            if kind != "token":
                return None     # busy/shed/error: defer, never crash
            _rid, tok, lp = payload
            toks.append(int(tok))
            lps.append(float(lp))
            if done:
                return toks, lps

    def _post_handler(self, route):
        return self._complete if route == "/v1/completions" else None

    # ---- the completions POST -------------------------------------------
    def _parse_completion(self, req):
        """Request JSON -> (ids, params, n, want_logprobs); raises
        ValueError/TypeError on client errors (the 400 path)."""
        ids = self._prompt_ids(req)
        max_tokens = int(req.get("max_tokens", 16))
        if max_tokens < 1:
            # the engine checks budgets only post-append, so
            # max_tokens=0 would come back with ONE token —
            # reject here instead (OpenAI also 400s it)
            raise ValueError("max_tokens must be >= 1")
        params = dict(max_new_tokens=max_tokens)
        if ("temperature" in req or "top_p" in req
                or "top_k" in req or req.get("do_sample")):
            params.update(
                do_sample=True,
                temperature=float(req.get("temperature", 1.0)),
                top_k=int(req.get("top_k", 0)),
                top_p=float(req.get("top_p", 1.0)))
        stop = req.get("stop_token_ids")
        if stop is not None:
            params["stop_token_ids"] = [int(s) for s in stop]
        # SLO-aware scheduling: priority class (lower = more important)
        # and a per-request latency target, straight through to the
        # engine's admission queue (docs/SERVING.md "Scheduling & SLOs")
        if req.get("priority") is not None:
            params["priority"] = int(req["priority"])
        if req.get("slo_ms") is not None:
            slo = float(req["slo_ms"])
            if slo <= 0:
                raise ValueError("slo_ms must be > 0")
            params["slo_ms"] = slo
        # the caller's request identity (the cluster router stamps one
        # on every placement): what the engine's deathnote names, so a
        # poison request is blamed consistently across workers/retries
        if req.get("request_id") is not None:
            params["request_id"] = str(req["request_id"])
        # OpenAI "logprobs" is an int 0-5 (0 = chosen-token
        # logprobs, no alternatives) or a bool — False means
        # OFF, any other non-None value (0 included) is ON
        lp_req = req.get("logprobs")
        want_logprobs = (lp_req is not None and lp_req is not False)
        if want_logprobs:
            params["logprobs"] = True
        n = int(req.get("n", 1))
        if n < 1:
            raise ValueError("n must be >= 1")
        if n > 1 and req.get("stream"):
            raise ValueError("n > 1 does not combine with stream")
        if n > 1:
            # validate the EFFECTIVE sampling config (engine
            # defaults merged with request overrides) — n
            # deterministic completions would be identical
            eng_s, eng_t, _, _ = self.engine._sample_cfg
            eff_s = params.get("do_sample", eng_s)
            eff_t = params.get("temperature", eng_t)
            if not eff_s or eff_t <= 0:
                raise ValueError(
                    "n > 1 needs effective sampling "
                    "(do_sample with temperature > 0) — n "
                    "deterministic completions would be "
                    "identical")
        px = req.get("pixel_values")
        if px is not None:
            # multimodal request (LLaVA): nested lists
            # [n_images, C, H, W] -> the engine's jitted
            # merge + embeds prefill
            arr = np.asarray(px, np.float32)
            if arr.ndim != 4:
                raise ValueError(
                    "pixel_values must be a nested list of "
                    "shape [n_images, C, H, W]")
            params["pixel_values"] = arr
        return ids, params, n, want_logprobs

    def _complete(self, handler, req):
        try:
            ids, params, n, want_logprobs = self._parse_completion(req)
        except (ValueError, TypeError) as e:
            # wrong-typed fields answer 400, not a dropped socket
            return handler._json(400, {"error": str(e)})
        # the on-demand audit contract: X-Audit: 1 (or body audit=true)
        # guarantees a shadow audit whose verdict block rides the
        # response next to usage — docs/SERVING.md "Correctness sentinel"
        hdr = (handler.headers.get(AUDIT_HEADER) or "").strip().lower()
        want_audit = bool(req.get("audit")) or hdr in ("1", "true")
        if want_audit:
            params["audit"] = True
        err = apply_deadline_header(handler, params)
        if err is not None:
            return handler._json(*err)
        sp = handler._trace_span
        sub = _Submission(ids, params, n=n,
                          trace_ctx=((sp.trace_id, sp.span_id)
                                     if sp is not None else None))
        self._subs.put(sub)
        cid = f"cmpl-{uuid.uuid4().hex[:24]}"
        if req.get("stream"):
            return self._stream(handler, sub, cid, want_logprobs,
                                want_audit=want_audit)
        return self._collect(handler, sub, cid, len(ids), want_logprobs,
                             want_audit=want_audit)

    def _collect(self, handler, sub, cid, n_prompt, want_logprobs,
                 prior_tokens=None, prior_logprobs=None,
                 want_audit=False):
        """Batch (non-stream) response: wait for every token event, then
        answer one completion object. ``prior_tokens``/``prior_logprobs``
        prepend a migrated-in request's already-generated tokens (the
        engine only fires on_token for NEW ones)."""
        by_rid, lps_by_rid, err = {}, {}, None
        finished = 0
        while True:
            try:
                kind, payload, done = sub.events.get(timeout=1.0)
            except queue.Empty:
                if self._stop.is_set():
                    return handler._json(500, {"error": "engine stopped"})
                continue
            if kind == "busy":
                # bounded admission queue: backpressure, not failure —
                # the client should retry after the hinted delay
                return handler._json(
                    429, {"error": payload["error"]},
                    headers=(("Retry-After", str(payload["retry_after"])),))
            if kind == "shed":
                # the engine dropped this request from its queue:
                # siblings of an n>1 submission are cancelled (one
                # atomic answer), and the status is typed — 429 for a
                # capacity displacement or an OOM degrade (both
                # retryable backpressure; the degrade 429 carries
                # code=engine_degraded), 504 for a spent deadline
                # (terminal)
                self._subs.put(_Cancel(sub))
                if payload.get("where") in ("capacity", "oom"):
                    ra = max(1, round(float(payload.get("retry_after",
                                                        1.0))))
                    body = {"error": payload["error"]}
                    if payload["where"] == "oom":
                        body["code"] = "engine_degraded"
                    return handler._json(
                        429, body,
                        headers=(("Retry-After", str(ra)),))
                return handler._json(
                    504, {"error": payload["error"],
                          "code": "deadline_exceeded"})
            if kind == "migrated":
                # the request left this worker mid-decode (drain): hand
                # the caller the handoff coordinates so the cluster
                # router can collect the continuation from the
                # destination worker
                return handler._json(200, {"migrated": payload})
            if kind in ("error", "fault"):
                err = (kind, payload)
                break
            rid, tok, lp = payload
            by_rid.setdefault(rid, []).append(int(tok))
            lps_by_rid.setdefault(rid, []).append(float(lp))
            if done:
                finished += 1
                if finished == sub.n:
                    break
        if err is not None:
            kind, msg = err
            return handler._json(400 if kind == "error" else 500,
                                 {"error": msg})
        choices = []
        total_completion = 0
        for i, rid in enumerate(sub.rids):
            toks = by_rid.get(rid, [])
            if i == 0 and prior_tokens:
                toks = list(prior_tokens) + toks
                lps_by_rid[rid] = (list(prior_logprobs or [])
                                   + lps_by_rid.get(rid, []))
            total_completion += len(toks)
            # single source of truth: the ENGINE records why each
            # request retired (recorded before its done event)
            choice = {"index": i,
                      "finish_reason": (self.engine.finish_reason(rid)
                                        or "length"),
                      "token_ids": toks}
            if want_logprobs:
                choice["logprobs"] = {
                    "token_logprobs": lps_by_rid.get(rid, [])}
            if self.tokenizer is not None:
                choice["text"] = self.tokenizer.decode(toks)
            choices.append(choice)
        usage = {"prompt_tokens": n_prompt,
                 "completion_tokens": total_completion,
                 "total_tokens": n_prompt + total_completion}
        usage.update(self._usage_extras(sub.rids))
        body = {
            "id": cid, "object": "text_completion",
            "model": self.model_name,
            "choices": choices,
            "usage": usage,
        }
        if want_audit:
            body["audit"] = self._audit_block(sub.rids)
        return handler._json(200, body)

    def _audit_block(self, rids) -> dict:
        """The ``audit`` response field of a force-audited request:
        block (bounded) for each rid's verdict and report the worst —
        diverged beats skipped beats pass. An on-demand audit is never
        silently absent: a disabled sentinel or a timed-out wait still
        answers a typed ``skipped`` verdict."""
        sn = self._sentinel
        if sn is None or not sn.enabled:
            return {"verdict": "skipped", "reason": "disabled"}
        vs = [v for v in (sn.wait_verdict(r) for r in rids)
              if v is not None]
        if not vs:
            return {"verdict": "skipped", "reason": "timeout"}
        worst = next((v for v in vs if v["verdict"] == "diverged"),
                     next((v for v in vs if v["verdict"] == "skipped"),
                          vs[0]))
        out = {k: worst.get(k)
               for k in ("verdict", "reason", "source",
                         "first_divergence", "logprob_drift")}
        if worst.get("bundle"):
            out["bundle"] = worst["bundle"]
        return out

    def _usage_extras(self, rids) -> dict:
        """Per-request cost accounting from the engine's retention
        window (queue vs compute milliseconds, fused dispatches ridden,
        tokens retired per dispatch). Across an n>1 submission the
        dispatches sum and the wall-clock fields take the max — the
        siblings decode concurrently. Empty when every rid already left
        the engine's retention window."""
        rows = [u for u in (self.engine.request_usage(r) for r in rids)
                if u is not None]
        if not rows:
            return {}
        disp = sum(u["dispatches"] for u in rows)
        n_tok = sum(u["completion_tokens"] for u in rows)
        return {
            "queue_ms": round(max(u["queue_ms"] for u in rows), 3),
            "compute_ms": round(max(u["compute_ms"] for u in rows), 3),
            "dispatches": disp,
            "accepted_tokens_per_dispatch": round(
                n_tok / disp if disp else 0.0, 4),
        }

    def _stream(self, handler, sub, cid, want_logprobs=False,
                want_audit=False):
        # the SSE status line is DEFERRED to the first event: a rejected
        # admission (bounded queue -> 429 + Retry-After) or a client
        # error (-> 400) still gets a real status code instead of an
        # error chunk inside a 200 stream. Once token bytes are on the
        # wire, failures become in-stream error events (no [DONE]).
        try:
            started = False
            clean = True
            while True:
                try:
                    kind, payload, done = sub.events.get(timeout=1.0)
                except queue.Empty:
                    if self._stop.is_set():
                        if not started:
                            return handler._json(
                                500, {"error": "engine stopped"})
                        handler._chunk(b'data: '
                                       b'{"error": "engine stopped"}\n\n')
                        clean = False
                        break
                    continue
                if kind == "busy":
                    # admission precedes tokens, so busy only ever
                    # arrives before the stream starts
                    return handler._json(
                        429, {"error": payload["error"]},
                        headers=(("Retry-After",
                                  str(payload["retry_after"])),))
                if kind == "shed":
                    # usually pre-admission (real 429/504 status line);
                    # a preempted-then-requeued stream can shed AFTER
                    # tokens flowed — then it ends with a typed error
                    # chunk and no [DONE]
                    if not started:
                        if payload.get("where") in ("capacity", "oom"):
                            ra = max(1, round(float(
                                payload.get("retry_after", 1.0))))
                            body = {"error": payload["error"]}
                            if payload["where"] == "oom":
                                body["code"] = "engine_degraded"
                            return handler._json(
                                429, body,
                                headers=(("Retry-After", str(ra)),))
                        return handler._json(
                            504, {"error": payload["error"],
                                  "code": "deadline_exceeded"})
                    handler._chunk(
                        b"data: "
                        + json.dumps(dict(_deadline_response(),
                                          shed=payload.get("where"))
                                     ).encode() + b"\n\n")
                    clean = False
                    break
                if kind == "migrated":
                    # the request left this worker mid-decode (drain):
                    # end the stream with a migrate marker and NO [DONE]
                    # — the cluster router resumes the relay on the
                    # destination worker; a direct client treats it like
                    # an unfinished stream
                    if not started:
                        handler._begin_sse()
                        started = True
                    handler._chunk(
                        b"data: "
                        + json.dumps({"migrated": payload}).encode()
                        + b"\n\n")
                    clean = False
                    break
                if kind in ("error", "fault"):
                    if not started:
                        return handler._json(
                            400 if kind == "error" else 500,
                            {"error": str(payload)})
                    handler._chunk(b'data: {"error": '
                                   + json.dumps(str(payload)).encode()
                                   + b"}\n\n")
                    clean = False
                    break
                if not started:
                    handler._begin_sse()
                    started = True
                _rid, tok, lp = payload
                piece = {"id": cid, "object": "text_completion",
                         "choices": [{"index": 0,
                                      "token_ids": [int(tok)]}]}
                if want_logprobs:
                    piece["choices"][0]["logprobs"] = {
                        "token_logprobs": [float(lp)]}
                if self.tokenizer is not None:
                    piece["choices"][0]["text"] = (
                        self.tokenizer.decode([int(tok)]))
                if done:
                    # the final pre-[DONE] payload carries the usage
                    # block (token counts + the engine's cost
                    # accounting, same shape as the non-stream
                    # response's usage field) ON the last token chunk
                    # rather than in an extra empty-choices event —
                    # clients that index choices[0] on every event
                    # keep working unmodified
                    rows = [u for u in (self.engine.request_usage(r)
                                        for r in sub.rids)
                            if u is not None]
                    if rows:
                        n_tok = sum(u["completion_tokens"] for u in rows)
                        piece["usage"] = {
                            "prompt_tokens": rows[0]["prompt_tokens"],
                            "completion_tokens": n_tok,
                            "total_tokens": (rows[0]["prompt_tokens"]
                                             + n_tok)}
                        piece["usage"].update(
                            self._usage_extras(sub.rids))
                    if want_audit:
                        # the final usage chunk carries the on-demand
                        # audit verdict, same shape as the non-stream
                        # response's audit field
                        piece["audit"] = self._audit_block(sub.rids)
                handler._chunk(b"data: " + json.dumps(piece).encode()
                               + b"\n\n")
                if done:
                    break
            if clean:
                # [DONE] signals CLEAN completion only — an SSE
                # client watching for it must not mistake a failed
                # stream for success
                handler._chunk(b"data: [DONE]\n\n")
            handler._chunk(b"")  # chunked-encoding terminator
        except OSError:
            # client went away mid-stream (BrokenPipeError /
            # reset): the engine must not keep decoding into a
            # dead socket — enqueue a cancel command to the
            # engine thread (it owns all device state), which
            # frees the slot(s) immediately and ends the
            # request's root span with status=cancelled
            self._subs.put(_Cancel(sub))
            if handler._trace_span is not None:
                handler._trace_span.set_attr("client_disconnected", True)
            handler.close_connection = True

    def _prompt_ids(self, req):
        if "prompt_token_ids" in req:
            ids = req["prompt_token_ids"]
            if (not isinstance(ids, list)
                    or not all(isinstance(i, int) for i in ids)):
                raise ValueError("prompt_token_ids must be a list of ints")
            return ids
        prompt = req.get("prompt")
        if prompt is None:
            raise ValueError("provide prompt or prompt_token_ids")
        if self.tokenizer is None:
            raise ValueError(
                "string prompts need the server constructed with a "
                "tokenizer; send prompt_token_ids instead")
        return list(self.tokenizer.encode(prompt))


def serve(model, *, max_batch=8, max_len=512, page_size=16, tokenizer=None,
          host="127.0.0.1", port=8000, **engine_kwargs):
    """One-call deployment: build the engine, start the server, block.

    >>> from paddle_tpu.serving_http import serve
    >>> serve(model, tokenizer=tok, port=8000)      # doctest: +SKIP
    """
    from .serving import ContinuousBatchEngine

    eng = ContinuousBatchEngine(model, max_batch=max_batch, max_len=max_len,
                                page_size=page_size, **engine_kwargs)
    srv = CompletionServer(eng, tokenizer=tokenizer, host=host, port=port)
    srv.start()
    try:
        srv._http_thread.join()
    except KeyboardInterrupt:
        srv.close()
    return srv
