"""paddle.sysconfig parity: include/lib dirs of the installed package."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Headers directory (native core sources live under core/csrc)."""
    return os.path.join(_ROOT, "core", "csrc")


def get_lib() -> str:
    """Directory holding the built native libraries (ctypes .so cache)."""
    return os.path.join(_ROOT, "core", "_build")
