"""paddle.hub parity (python/paddle/hub.py): load entrypoints from a
hubconf.py. This environment has no network egress, so only the 'local'
source works; 'github'/'gitee' raise with instructions (same failure mode
as the reference without connectivity).
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise RuntimeError(f"no {_HUBCONF} found in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def _resolve(repo_dir: str, source: str):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network egress, which this "
            "environment does not have; clone the repo and use "
            "source='local'")
    return _load_hubconf(repo_dir)


def list(repo_dir: str, source: str = "github", force_reload: bool = False):  # noqa: A001
    """Entrypoint names exported by the repo's hubconf."""
    mod = _resolve(repo_dir, source)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",  # noqa: A001
         force_reload: bool = False):
    mod = _resolve(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None:
        raise RuntimeError(f"no entrypoint {model!r} in {repo_dir}")
    return fn.__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    mod = _resolve(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None:
        raise RuntimeError(f"no entrypoint {model!r} in {repo_dir}")
    return fn(**kwargs)
