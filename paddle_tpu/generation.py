"""Text generation: static KV-cache decode, sampling, paged attention.

Reference parity: the serving slice the reference builds from
- block_multi_head_attention (paged KV cache decode kernel,
  paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu),
- top_p_sampling (paddle/phi/kernels/gpu/top_p_sampling_kernel.cu /
  python/paddle/tensor/random.py top_p_sampling),
- PaddleNLP's GenerationMixin greedy/sampling loops.

TPU-native design: the KV cache is a STATIC-shape buffer per layer —
dense [B, max_len, kv_heads, head_dim] or paged (block tables) — updated
with dynamic_update_slice/scatter, and the whole decode step (embed →
layers → lm head → cache update) is ONE jitted computation with the cache
buffers donated, so each generated token is a single device dispatch and
the buffers are updated in place. The paged layout matches JAX's bundled
Pallas paged_attention kernel, which is used on TPU (jnp gather reference
elsewhere).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tensor_class import Tensor, unwrap, wrap
from .ops.registry import apply
from .autograd import tape as _tape
from .framework import random as _random
from .nn.layer import functional_weights as _functional_weights


# ---------------------------------------------------------------------------
# cache attention kernels (dense + paged)
# ---------------------------------------------------------------------------

def _rope_rows(x, cos, sin, row_pos):
    """RoPE with PER-ROW positions: x [B,S,H,D], row_pos [B] — row b's
    token s sits at absolute position row_pos[b]+s (ragged decode);
    width-aware via partial_rope."""
    from .ops.pallas.fused_norm import partial_rope

    return partial_rope(_rope_rows_full, x, cos, sin, row_pos)


def _rope_rows_full(x, cos, sin, row_pos):
    S = x.shape[1]
    d = x.shape[-1]
    idx = row_pos[:, None] + jnp.arange(S)[None, :]        # [B, S]
    cos_b = cos[idx]                                       # [B, S, D]
    sin_b = sin[idx]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    c = cos_b[:, :, None, :]
    s = sin_b[:, :, None, :]
    return (x.astype(jnp.float32) * c + rotated.astype(jnp.float32) * s
            ).astype(x.dtype)


def cached_attention(q, k, v, cos, sin, k_buf, v_buf, pos, allowed=None,
                     row_pos=None, use_flash=False, interpret=False,
                     prefill=False, window=None, softcap=None,
                     rope_applied=False):
    """RoPE + cache write + masked GQA attention against a dense buffer.

    q [B,S,H,D]; k/v [B,S,hk,D]; cos/sin [>=max_len, D];
    k_buf/v_buf [B,Smax,hk,D]; pos = buffer write offset (scalar);
    allowed = optional [B,Tmax] column-validity mask (padded prompts);
    row_pos = optional [B] per-row RoPE positions (ragged batches);
    use_flash = route an unpadded pos=0 prefill (the serving hot path)
    through the GQA splash flash kernel instead of the dense einsum against
    the whole buffer — at pos=0 prefill, causal attention over the prompt
    equals causal self-attention on the S new tokens, so the flash kernel
    is exact and never touches the (mostly empty) Smax buffer.
    ``rope_applied``: q/k arrive already rotated (the fused decode-tail
    kernel ropes in-register) — skip the rope, keep everything else.
    Returns (out [B,S,H,D], new_k_buf, new_v_buf).
    """
    from .ops.pallas.fused_norm import rope_ref

    B, S, H, D = q.shape
    hk = k_buf.shape[2]
    pos = jnp.asarray(pos, jnp.int32)
    if rope_applied:
        pass
    elif row_pos is None:
        cos_s = jax.lax.dynamic_slice_in_dim(cos, pos, S, 0)
        sin_s = jax.lax.dynamic_slice_in_dim(sin, pos, S, 0)
        q = rope_ref(q, cos_s, sin_s)
        k = rope_ref(k, cos_s, sin_s)
    else:
        q = _rope_rows(q, cos, sin, row_pos)
        k = _rope_rows(k, cos, sin, row_pos)
    k_buf = jax.lax.dynamic_update_slice(
        k_buf, k.astype(k_buf.dtype), (0, pos, 0, 0))
    v_buf = jax.lax.dynamic_update_slice(
        v_buf, v.astype(v_buf.dtype), (0, pos, 0, 0))

    if use_flash and S > 1 and allowed is None and row_pos is None:
        from .ops.pallas import flash_attention as pf

        # `prefill` is the STATIC marker _empty_caches stamps on fresh
        # (pos=0) caches — it survives jit tracing, where even jnp
        # constants are tracers and a value check would always fail
        pos_is_zero = bool(prefill)
        if not pos_is_zero:
            try:
                pos_is_zero = int(pos) == 0  # eager caller: concrete scalar
            except Exception:  # pdlint: disable=silent-exception -- int() on a traced offset raises by design (TracerError); 'unknown, stay dense' is the correct conservative branch, not a fault
                pos_is_zero = False  # traced offset: unknown, stay dense
        if pos_is_zero and pf.supported(q, k, v, interpret=interpret):
            out = pf.flash_attention_bshd(q, k, v, causal=True,
                                          interpret=interpret, window=window)
            return out.astype(q.dtype), k_buf, v_buf

    if use_flash and S > 1 and window is None:
        # multi-token append at pos >= 0 (chunked prefill, speculative
        # verify): streaming-softmax Pallas kernel over the buffer, blocks
        # beyond pos+S skipped — replaces the dense full-buffer einsum
        from .ops.pallas import append_attention as pa

        if pa.supported(q, k_buf, interpret=interpret):
            out = pa.append_attention(q, k_buf, v_buf, pos, allowed=allowed,
                                      interpret=interpret)
            return out.astype(q.dtype), k_buf, v_buf

    g = H // hk
    scale = 1.0 / math.sqrt(D)
    qg = q.reshape(B, S, hk, g, D)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg.astype(jnp.float32),
                        k_buf.astype(jnp.float32)) * scale
    if softcap is not None:
        # Gemma2 tanh soft cap, applied before masking (HF order)
        scores = softcap * jnp.tanh(scores / softcap)
    T = k_buf.shape[1]
    t_idx = jnp.arange(T)
    s_idx = jnp.arange(S)
    valid = t_idx[None, :] <= (pos + s_idx)[:, None]        # [S, T]
    if window is not None and allowed is None and row_pos is None:
        # sliding window, contiguous layout: column t visible from row
        # (pos+s) only while t > (pos+s) - window
        valid = valid & (t_idx[None, :] > (pos + s_idx)[:, None] - window)
    mask = valid[None, None, None]                          # [1,1,1,S,T]
    if allowed is not None:
        mask = mask & allowed[:, None, None, None, :]       # [B,1,1,S,T]
    if window is not None and (allowed is not None or row_pos is not None):
        # ragged (right-padded) layout: buffer distance != token distance —
        # a short row's prompt sits at slots 0..len-1 while decode writes at
        # the SHARED offset pos, so the window must count TRUE positions:
        # column t's position in row b is the number of allowed columns
        # before it (pads excluded), and the query at buffer slot pos+s has
        # position colpos[b, pos+s]
        base = (allowed.astype(jnp.int32) if allowed is not None
                else jnp.ones((B, T), jnp.int32))
        colpos = jnp.cumsum(base, axis=1) - 1                # [B, T]
        curpos = jax.lax.dynamic_slice_in_dim(colpos, pos, S, 1)  # [B, S]
        win_ok = colpos[:, None, :] > curpos[:, :, None] - window  # [B, S, T]
        mask = mask & win_ok[:, None, None]                  # [B,1,1,S,T]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgst,btkd->bskgd", probs,
                     v_buf.astype(jnp.float32))
    return out.reshape(B, S, H, D).astype(q.dtype), k_buf, v_buf


def paged_cached_attention(q, k, v, cos, sin, k_pages, v_pages, page_indices,
                           lengths, page_size, window=None, softcap=None,
                           rope_applied=False):
    """Multi-token decode over the PAGED cache (in-layer dispatch).

    q [B,S,H,D]; pages [hk, n_pages, page_size, D]; lengths [B] = tokens
    already present PER ROW. Fully ragged: row b's token j is RoPE'd at
    position lengths[b]+j and written at its own page/slot
    (page_indices[b, pos//ps], pos%ps) — the block_multi_head_attention
    write pattern, which is what lets a continuous-batching server mix
    requests of different lengths in one step. S == 1 is the classic
    decode step; S > 1 is the speculative-verify chunk (each chunk
    position attends the cache plus the chunk prefix before it — the
    chunk-causal mask). ``rope_applied``: q/k arrive already rotated
    (fused decode tail) — skip the per-row rope, keep the write +
    attention.
    """
    B, S = q.shape[0], q.shape[1]
    lengths = jnp.asarray(lengths, jnp.int32)
    if not rope_applied:
        q = _rope_rows(q, cos, sin, lengths)
        k = _rope_rows(k, cos, sin, lengths)
    if S == 1:
        page = lengths // page_size                 # [B]
        slot = lengths % page_size                  # [B]
        rows = page_indices[jnp.arange(B), page]    # [B]
        k_pages = k_pages.at[:, rows, slot].set(
            jnp.moveaxis(k[:, 0], 0, 1).astype(k_pages.dtype))
        v_pages = v_pages.at[:, rows, slot].set(
            jnp.moveaxis(v[:, 0], 0, 1).astype(v_pages.dtype))
        out = paged_decode_attention(q[:, 0], k_pages, v_pages, lengths + 1,
                                     page_indices, window=window,
                                     softcap=softcap)
        return out[:, None], k_pages, v_pages
    # speculative-verify chunk: scatter all S tokens at per-row positions
    # lengths[b]+j, then chunk-causal attention over the gathered pages.
    # Rejected-suffix KV lands ABOVE the row's post-accept frontier, where
    # the next chunk's scatter overwrites it before lengths can reach it —
    # the same parking invariant chunked prefill relies on.
    pos = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B,S]
    page = pos // page_size
    slot = pos % page_size
    rows = jnp.take_along_axis(page_indices, page, axis=1)            # [B,S]
    k_pages = k_pages.at[:, rows, slot].set(
        jnp.moveaxis(k, 2, 0).astype(k_pages.dtype))
    v_pages = v_pages.at[:, rows, slot].set(
        jnp.moveaxis(v, 2, 0).astype(v_pages.dtype))
    out = _paged_chunk_attention(q, k_pages, v_pages, lengths, page_indices,
                                 window=window, softcap=softcap)
    return out, k_pages, v_pages


def _paged_chunk_attention(q, k_pages, v_pages, lengths, page_indices,
                           window=None, softcap=None):
    """Chunk attention over the paged cache: q [B,S,H,D] sits at per-row
    positions lengths[b]+j; column t is visible from chunk position j iff
    t <= lengths[b]+j (and, windowed, t > lengths[b]+j-window). XLA
    gather + MXU matmul, exact vs the dense reference — the S=1 Pallas
    decode kernel has no chunk-causal mask, so the verify chunk takes
    this path on every backend."""
    B, S = q.shape[0], q.shape[1]
    hk, _n, page_size, D = k_pages.shape
    k = jnp.moveaxis(k_pages[:, page_indices], 0, 1)  # [B,hk,pages,ps,D]
    v = jnp.moveaxis(v_pages[:, page_indices], 0, 1)
    T = k.shape[2] * page_size
    k = k.reshape(B, hk, T, D)
    v = v.reshape(B, hk, T, D)
    qpos = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B,S]
    t_idx = jnp.arange(T)
    valid = t_idx[None, None, :] <= qpos[:, :, None]                # [B,S,T]
    if window is not None:
        valid &= t_idx[None, None, :] > (qpos[:, :, None] - window)
    return _chunk_sdpa(q, k, v, valid, softcap=softcap)


def paged_decode_attention(q, k_pages, v_pages, lengths, page_indices,
                           pages_per_compute_block=None, window=None,
                           softcap=None):
    """Decode attention over a paged cache: JAX's bundled Pallas kernel on
    TPU, a jnp gather reference (identical semantics) elsewhere.

    ``window`` (Mistral sliding-window serving): only the last ``window``
    positions attend. The bundled Pallas kernel has no lower-bound
    masking, so windowed rows take the O(window) page-gather path
    (_paged_window_attention: only the <= ceil(window/page_size)+1 pages
    the band intersects are read — HBM cost scales with the window, not
    the cache capacity).

    ``pages_per_compute_block`` defaults to the largest divisor of
    pages-per-sequence <= 8: bigger blocks amortize the kernel's grid
    overhead across more of the KV stream (HBM-bandwidth-bound op)."""
    if window is not None:
        cache_positions = page_indices.shape[1] * k_pages.shape[2]
        if window < cache_positions:
            # gather ONLY the pages the band can touch: O(window) work
            # regardless of max_len — the win windowed serving exists for
            return _paged_window_attention(q, k_pages, v_pages, lengths,
                                           page_indices, window,
                                           softcap=softcap)
        # else: the band can never exclude a cached position (window >=
        # cache capacity) — fall through to the fused Pallas kernel,
        # e.g. Mistral-7B's 4096 window served at max_len <= 4096
    if softcap is not None:
        # the bundled Pallas kernel computes uncapped scores; the exact
        # gather reference (O(cache) reads) keeps softcapped models
        # (Gemma2) servable through the paged engine
        return _paged_attention_ref(q, k_pages, v_pages, lengths,
                                    page_indices, softcap=softcap)
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:  # pdlint: disable=silent-exception -- backend probe: jax.devices() raising (no backend initialised) means 'not on TPU'; the reference path below is the designed fallback
        on_tpu = False
    if on_tpu:
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as pa)

        if pages_per_compute_block is None:
            pages_per_seq = page_indices.shape[1]
            pages_per_compute_block = next(
                b for b in (8, 4, 2, 1) if pages_per_seq % b == 0)
        return pa.paged_attention(
            q, k_pages, v_pages, lengths, page_indices,
            pages_per_compute_block=pages_per_compute_block)
    return _paged_attention_ref(q, k_pages, v_pages, lengths, page_indices)


def _paged_window_attention(q, k_pages, v_pages, lengths, page_indices,
                            window, softcap=None):
    """Sliding-window decode over the paged cache, touching only the
    pages the band intersects (≤ ceil(window/page_size)+1 per row): HBM
    reads scale with the WINDOW, not the cache capacity — the long-
    context property windowed serving exists for. Pure XLA (gather +
    MXU matmul), exact vs the full-gather reference."""
    B = q.shape[0]
    hk, _n, page_size, _ = k_pages.shape
    wp = (window + page_size - 1) // page_size + 1     # pages the band spans
    n_pages_per_row = page_indices.shape[1]
    wp = min(wp, n_pages_per_row)
    # first page the band can touch (band = [len-window, len-1])
    first = jnp.maximum(lengths - window, 0) // page_size        # [B]
    first = jnp.minimum(first, jnp.maximum(n_pages_per_row - wp, 0))
    offs = first[:, None] + jnp.arange(wp)[None, :]              # [B, wp]
    rows = jnp.take_along_axis(page_indices, offs, axis=1)       # [B, wp]
    k = jnp.moveaxis(k_pages[:, rows], 0, 1)     # [B, hk, wp, ps, D]
    v = jnp.moveaxis(v_pages[:, rows], 0, 1)
    W = wp * page_size
    k = k.reshape(B, hk, W, k_pages.shape[-1])
    v = v.reshape(B, hk, W, v_pages.shape[-1])
    # global position of each gathered column
    colpos = (offs[:, :, None] * page_size
              + jnp.arange(page_size)[None, None, :]).reshape(B, W)
    valid = (colpos < lengths[:, None]) & \
            (colpos >= (lengths[:, None] - window))
    return _banded_sdpa(q, k, v, valid, softcap=softcap)


def _banded_sdpa(q, k, v, valid, softcap=None):
    """Shared decode-attention tail: q [B,H,D], k/v [B,hk,T,D] gathered,
    valid [B,T] column mask — the S=1 view of :func:`_chunk_sdpa` (the
    ONE place the f32 softmax numerics of the paged decode paths live)."""
    return _chunk_sdpa(q[:, None], k, v, valid[:, None],
                       softcap=softcap)[:, 0]


def _chunk_sdpa(q, k, v, valid, softcap=None):
    """Decode/verify attention core: q [B,S,H,D] against gathered k/v
    [B,hk,T,D] with a per-position column mask valid [B,S,T]. f32 scores
    and softmax; ``softcap``: Gemma2 tanh soft cap on the scaled scores,
    applied before masking (HF order)."""
    B, S, H, D = q.shape
    hk = k.shape[1]
    g = H // hk
    qg = q.reshape(B, S, hk, g, D).astype(jnp.float32)
    scores = jnp.einsum("bskgd,bktd->bkgst", qg, k.astype(jnp.float32))
    scores = scores / math.sqrt(D)
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    scores = jnp.where(valid[:, None, None, :, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgst,bktd->bskgd", probs, v.astype(jnp.float32))
    return out.reshape(B, S, H, D).astype(q.dtype)


def _paged_attention_ref(q, k_pages, v_pages, lengths, page_indices,
                         window=None, softcap=None):
    B, H, D = q.shape
    hk, _n, page_size, _ = k_pages.shape
    g = H // hk
    k = jnp.moveaxis(k_pages[:, page_indices], 0, 1)  # [B, hk, pages, ps, D]
    v = jnp.moveaxis(v_pages[:, page_indices], 0, 1)
    T = k.shape[2] * page_size
    k = k.reshape(B, hk, T, D)
    v = v.reshape(B, hk, T, D)
    valid = jnp.arange(T)[None, :] < lengths[:, None]
    if window is not None:
        # band lower bound: only the newest `window` positions attend
        valid &= jnp.arange(T)[None, :] >= (lengths[:, None] - window)
    return _banded_sdpa(q, k, v, valid, softcap=softcap)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def _top_k_filter(logits, k):
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _top_p_filter(logits, p):
    if p >= 1.0:
        return logits
    srt = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the first)
    keep = jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < p], -1)
    min_logit = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < min_logit, -jnp.inf, logits)


def sample_logits(logits, key, do_sample=False, temperature=1.0,
                  top_k=0, top_p=1.0):
    """Next-token selection from [B, V] logits (pure)."""
    logits = logits.astype(jnp.float32)
    # temperature ~ 0 is greedy (matches sample_logits_rows): dividing by
    # the 1e-6 cap instead would hand near-tied runner-ups real probability
    if not do_sample or (not isinstance(temperature, jnp.ndarray)
                         and temperature <= 1e-6):
        return jnp.argmax(logits, axis=-1)
    if temperature != 1.0:
        logits = logits / jnp.maximum(temperature, 1e-6)
    logits = _top_k_filter(logits, int(top_k))
    logits = _top_p_filter(logits, float(top_p))
    return jax.random.categorical(key, logits, axis=-1)


def sample_logits_rows(logits, key, do_sample, temperature, top_k, top_p):
    """Per-ROW next-token selection from [B, V] logits: every sampling knob
    is a [B] array (the continuous-batching engine's per-request sampling —
    one compiled program serves any mix of greedy/temperature/top-k/top-p
    requests). Rows with do_sample=False take the plain argmax; top_k <= 0
    means no k-filter; top_p >= 1 means no nucleus filter."""
    lg = logits.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1)
    V = lg.shape[-1]
    x = lg / jnp.maximum(temperature, 1e-6)[:, None]
    # per-row top-k via the kth-value threshold (ties at the kth value are
    # kept, matching _top_k_filter's semantics)
    srt_desc = jnp.sort(x, axis=-1)[:, ::-1]
    idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(srt_desc, idx[:, None], axis=-1)  # [B, 1]
    kth = jnp.where(((top_k <= 0) | (top_k >= V))[:, None], -jnp.inf, kth)
    x = jnp.where(x < kth, -jnp.inf, x)
    # per-row top-p over the k-filtered distribution. The k-filter zeroes a
    # SUFFIX of the descending sort, so the sorted filtered logits (and
    # hence sorted probs) come from srt_desc directly — no second sort
    probs = jax.nn.softmax(x, axis=-1)
    srt = jax.nn.softmax(jnp.where(srt_desc < kth, -jnp.inf, srt_desc),
                         axis=-1)
    cum = jnp.cumsum(srt, axis=-1)
    keep = jnp.concatenate(
        [jnp.ones_like(cum[:, :1], bool), cum[:, :-1] < top_p[:, None]], -1)
    min_prob = jnp.min(jnp.where(keep, srt, jnp.inf), -1, keepdims=True)
    min_prob = jnp.where(top_p[:, None] >= 1.0, 0.0, min_prob)  # no filter
    x = jnp.where(probs < min_prob, -jnp.inf, x)
    sampled = jax.random.categorical(key, x, axis=-1)
    # temperature ~ 0 means greedy, not a 1e6x logit blow-up (ADVICE r4:
    # the division guard alone overflowed f32 to inf and degraded
    # jax.random.categorical)
    return jnp.where(do_sample & (temperature > 1e-6), sampled, greedy)


def top_p_sampling(x, ps, threshold=None, seed=None):
    """paddle.tensor.top_p_sampling parity (ops.yaml `top_p_sampling`):
    nucleus-sample one token per row of probabilities ``x`` [B, V] with
    per-row cutoffs ``ps`` [B]. Returns (scores, ids)."""
    key = (jax.random.key(seed) if seed is not None and seed >= 0
           else _random.next_key())

    def fn(probs, p):
        logits = jnp.log(jnp.maximum(probs, 1e-38))
        srt = jnp.sort(probs, axis=-1)[..., ::-1]
        cum = jnp.cumsum(srt, axis=-1)
        keep = jnp.concatenate(
            [jnp.ones_like(cum[..., :1], bool),
             cum[..., :-1] < p[..., None]], -1)
        min_prob = jnp.min(jnp.where(keep, srt, jnp.inf), -1, keepdims=True)
        filtered = jnp.where(probs < min_prob, -jnp.inf, logits)
        ids = jax.random.categorical(key, filtered, axis=-1)
        score = jnp.take_along_axis(probs, ids[..., None], -1)[..., 0]
        return score, ids

    return apply("top_p_sampling", fn, x, ps, differentiable=False)


@functools.partial(jax.jit, static_argnames=("do_sample", "temperature",
                                             "top_k", "top_p"))
def _select(logits_last, key, do_sample, temperature, top_k, top_p):
    return sample_logits(logits_last, key, do_sample=do_sample,
                         temperature=temperature, top_k=top_k, top_p=top_p)


@functools.partial(jax.jit, static_argnames=("do_sample", "temperature",
                                             "top_k", "top_p", "rp",
                                             "block_eos", "eos_id"))
def _select_penalized(logits_last, seen, key, do_sample, temperature, top_k,
                      top_p, rp, block_eos, eos_id):
    """_select with HF-semantics repetition penalty (positive logits of
    seen tokens divided by rp, negative multiplied) and an optional eos
    block (min_new_tokens phase)."""
    lg = logits_last.astype(jnp.float32)
    if rp != 1.0:
        pen = jnp.where(lg > 0, lg / rp, lg * rp)
        lg = jnp.where(seen, pen, lg)
    if block_eos:
        lg = lg.at[:, eos_id].set(-jnp.inf)
    return sample_logits(lg, key, do_sample=do_sample,
                         temperature=temperature, top_k=top_k, top_p=top_p)


class _NgramBan:
    """Incremental HF NoRepeatNGramLogitsProcessor: per row, a hash of
    (n-1)-gram prefix -> set of banned completions, updated O(1) per
    appended token. (ADVICE r4: the previous implementation rescanned the
    whole history every decode step — O(len^2) host work per token that
    serialized the loop.)"""

    def __init__(self, histories, n: int):
        self.n = n
        self.hist = [list(h) for h in histories]
        self.maps = [{} for _ in self.hist]
        for b, h in enumerate(self.hist):
            for j in range(len(h) - n + 1):
                self.maps[b].setdefault(tuple(h[j:j + n - 1]),
                                        set()).add(h[j + n - 1])

    def append(self, b: int, tok: int):
        h = self.hist[b]
        h.append(tok)
        if len(h) >= self.n:
            self.maps[b].setdefault(tuple(h[-self.n:-1]), set()).add(h[-1])

    def banned(self, vocab: int):
        """[B, V] mask of tokens that would complete an already-seen
        n-gram of each row's current suffix."""
        out = np.zeros((len(self.hist), vocab), bool)
        for b, h in enumerate(self.hist):
            if len(h) < self.n - 1 and self.n > 1:
                continue
            prefix = tuple(h[-(self.n - 1):]) if self.n > 1 else ()
            for t in self.maps[b].get(prefix, ()):
                out[b, t] = True
        return out


def _ngram_banned(histories, n, vocab):
    """[B, V] mask (one-shot form; the decode loops keep a _NgramBan)."""
    return _NgramBan(histories, n).banned(vocab)


def _select_next(last, seen, key, do_sample, temperature, top_k, top_p,
                 rp, i, min_new, eos_token_id):
    """One-call next-token selection: routes to the plain _select program
    whenever no penalty applies at step ``i`` (rp == 1 and the eos-block
    phase is over) — the marshalling shared by the cached and no-cache
    decode loops."""
    if rp == 1.0 and i >= min_new:
        return _select(last, key, do_sample, float(temperature), int(top_k),
                       float(top_p))
    return _select_penalized(
        last, seen if seen is not None else jnp.zeros((last.shape[0], 1), bool),
        key, do_sample, float(temperature), int(top_k), float(top_p), rp,
        i < min_new, int(eos_token_id) if eos_token_id is not None else -1)


def _seen_from_prompt(ids, vocab, pad_mask=None):
    """[B, V] flag of tokens present in each row's prompt (pad columns
    excluded) — the repetition-penalty working set."""
    B, S0 = ids.shape
    seen = jnp.zeros((B, vocab), bool)
    safe = ids.astype(jnp.int32)
    if pad_mask is not None:
        upd = pad_mask[:, :S0]
    else:
        upd = jnp.ones((B, S0), bool)
    return seen.at[jnp.arange(B)[:, None], safe].max(upd)


# ---------------------------------------------------------------------------
# decode step machinery
# ---------------------------------------------------------------------------

def _empty_caches(model, batch, max_len, allowed=None, row_pos=None):
    from .models.llama import head_dim_of

    cfg = model.config
    hk = cfg.num_key_value_heads
    d = head_dim_of(cfg)
    dt = jnp.dtype(cfg.dtype) if isinstance(cfg.dtype, str) else cfg.dtype
    # models with a non-k/v cache layout (MLA's compressed latent) provide
    # their own per-layer buffer allocator
    make = getattr(model.llama, "empty_cache_layer", None)
    caches = []
    for _ in range(cfg.num_hidden_layers):
        # pos starts as a PYTHON int so it stays a concrete constant even
        # when the prefill traces under jit — the flash fast path's
        # `int(pos) == 0` guard (cached_attention) must see through the
        # trace; decode steps then carry it as a traced scalar
        # "prefill": static marker consumed by the first forward (the
        # attention layer's `new` dict drops it), enabling the flash fast
        # path under jit; pos stays a python 0 so the first cache write
        # compiles as a static-offset slice
        if make is not None:
            c = dict(make(batch, max_len, dt), pos=0, prefill=True)
        else:
            c = {"k": jnp.zeros((batch, max_len, hk, d), dt),
                 "v": jnp.zeros((batch, max_len, hk, d), dt),
                 "pos": 0, "prefill": True}
        if allowed is not None:
            c["allowed"] = allowed
        if row_pos is not None:
            c["row_pos"] = row_pos
        caches.append(c)
    return caches


def _unwrap_caches(caches):
    return jax.tree_util.tree_map(
        lambda x: x._array if isinstance(x, Tensor) else x, caches,
        is_leaf=lambda x: isinstance(x, Tensor))


_BUF_KEYS = ("k", "v", "k_pages", "v_pages", "c_kv", "k_pe")


def _split_caches(caches):
    """Separate the big per-layer KV buffers (donatable — each layer owns
    its own) from the small shared aux values (page tables / masks /
    positions shared across layers must NOT be donated twice)."""
    bufs = [{k: c[k] for k in _BUF_KEYS if k in c} for c in caches]
    aux = [{k: v for k, v in c.items() if k not in _BUF_KEYS}
           for c in caches]
    return bufs, aux


def _cached_forward(model, max_len, state, token, bufs, aux):
    """The shared pure decode body: merge cache halves, run the cached
    forward under functional weights, split the updated caches back.
    Returns (logits, new_bufs, new_aux)."""
    caches = [{**b, **a} for b, a in zip(bufs, aux)]
    with _functional_weights(model, state), _tape.no_grad():
        hidden, new_caches = model.llama.forward_cached(
            wrap(token), caches, rope_len=max_len)
        logits = model.lm_head_logits(hidden)
    nb, na = _split_caches(_unwrap_caches(new_caches))
    return unwrap(logits), nb, na


class _DecodeStep:
    """ONE jitted computation per generated token: embed → all layers with
    in-place (donated) cache buffers → lm-head logits. The TrainStep
    pattern applied to decode (jit/__init__.py TrainStep)."""

    def __init__(self, model, max_len):
        self._model = model

        def pure(state, token, bufs, aux):
            return _cached_forward(model, max_len, state, token, bufs, aux)

        self._jitted = jax.jit(pure, donate_argnums=(2,))
        self._state = dict(model.functional_state())

    def __call__(self, token, caches):
        bufs, aux = _split_caches(caches)
        logits, nb, na = self._jitted(self._state, token, bufs, aux)
        return logits, [{**b, **a} for b, a in zip(nb, na)]


def _rows_match(a, n):
    """True for array leaves whose leading axis is the batch/beam rows —
    the one predicate shared by beam tiling and beam-reorder gathers."""
    return hasattr(a, "ndim") and a.ndim >= 1 and a.shape[0] == n


class _EncDecBeamStep:
    """Jitted enc-dec beam unit shared by T5/BART: gather the SELF-cache
    rows each surviving beam came from (cross caches are identical across
    a batch's K beams after tiling, so they stay untouched), run one
    cached decoder step, return next-position log-probs. ``decode`` is the
    family's cached decoder call:
    ``decode(model, token, self_caches, cross_caches) ->
    (hidden, new_self, _)``."""

    def __init__(self, model, decode):
        from .autograd import tape as _tape
        from .nn.layer import functional_weights

        def pure(state, token, row_idx, self_caches, cross_caches):
            n = row_idx.shape[0]
            take = lambda a: (jnp.take(a, row_idx, axis=0)
                              if _rows_match(a, n) else a)
            self_caches = jax.tree.map(take, self_caches)
            with functional_weights(model, state), _tape.no_grad():
                hidden, new_self, _ = decode(model, wrap(token),
                                             self_caches, cross_caches)
                logits = model.lm_head_logits(hidden)
            logp = jax.nn.log_softmax(
                unwrap(logits)[:, -1, :].astype(jnp.float32), axis=-1)
            return logp, [
                {k: (unwrap(v) if isinstance(v, Tensor) else v)
                 for k, v in c.items()} for c in new_self]

        self._jitted = jax.jit(pure, donate_argnums=(3,))
        self._state = dict(model.functional_state())

    def __call__(self, token, row_idx, self_caches, cross_caches):
        return self._jitted(self._state, token, row_idx, self_caches,
                            cross_caches)


def reject_sampled_beams(family: str, num_beams: int, do_sample: bool):
    """The enc-dec families' shared guard: beam search composes with
    greedy scoring only (raised BEFORE any encoder compute, so an
    argument error is free)."""
    if num_beams > 1 and do_sample:
        raise NotImplementedError(
            f"{family}.generate: beam search composes with greedy "
            "scoring only (do_sample=False)")


def encdec_beam_generate(model, decode, step0, token0, self_c, cross_c,
                         max_new_tokens, num_beams, eos_token_id,
                         length_penalty, early_stopping, cache_attr):
    """Beam search over a cached enc-dec decoder (T5/BART ``num_beams``):
    one plain cached step on the B rows scores the first position, caches
    tile to B*K rows, and the jitted _EncDecBeamStep reorders self caches
    by beam origin each subsequent step. Returns the padded [B, width]
    token Tensor (HF generate semantics, like the decoder-only path)."""
    import numpy as np

    B, K = token0.shape[0], num_beams
    logits, self_c = step0(token0, self_c, cross_c)
    logp0 = np.asarray(jax.nn.log_softmax(
        logits[:, -1, :].astype(jnp.float32), axis=-1))
    tile = lambda t: jax.tree.map(
        lambda a: jnp.repeat(a, K, axis=0) if _rows_match(a, B) else a, t)
    self_c, cross_c = tile(self_c), tile(cross_c)
    bstep = _memoized_step(model, cache_attr, (),
                           lambda: _EncDecBeamStep(model, decode))
    holder = {"self": self_c}

    def step(token, row_idx):
        logp, holder["self"] = bstep(token.astype(jnp.int32),
                                     jnp.asarray(row_idx), holder["self"],
                                     cross_c)
        # beam scoring runs on host by design: ONE fetch per beam step
        return np.asarray(logp)  # pdlint: disable=host-sync

    arr = beam_search_loop(logp0, step, max_new_tokens, K, eos_token_id,
                           length_penalty, early_stopping)
    return wrap(jnp.asarray(arr))


class _BeamStep:
    """Beam-search decode unit, ONE jitted dispatch per step: gather the
    cache rows each surviving beam came from (beam reordering), run the
    cached forward on the chosen tokens, return next log-probs."""

    def __init__(self, model, max_len):
        self._model = model

        def pure(state, token, row_idx, bufs, aux):
            take = lambda a: (jnp.take(a, row_idx, axis=0)
                              if _rows_match(a, row_idx.shape[0]) else a)
            bufs = jax.tree.map(take, bufs)
            aux = jax.tree.map(take, aux)
            logits, nb, na = _cached_forward(model, max_len, state, token,
                                             bufs, aux)
            logp = jax.nn.log_softmax(
                logits[:, -1, :].astype(jnp.float32), axis=-1)
            return logp, nb, na

        self._jitted = jax.jit(pure, donate_argnums=(3,))
        self._state = dict(model.functional_state())

    def __call__(self, token, row_idx, caches):
        bufs, aux = _split_caches(caches)
        logp, nb, na = self._jitted(self._state, token, row_idx, bufs, aux)
        return logp, [{**b, **a} for b, a in zip(nb, na)]


def _get_beam_step(model, max_len):
    return _memoized_step(model, "_beam_steps", (max_len,),
                          lambda: _BeamStep(model, max_len))


class _BeamHyps:
    """Per-batch pool of finished hypotheses (HF BeamHypotheses semantics:
    scores are sum-logprob / len**length_penalty over GENERATED tokens)."""

    def __init__(self, k, length_penalty, early_stopping):
        self.k, self.lp, self.early = k, length_penalty, early_stopping
        self.items = []  # (score, tokens list)

    def add(self, sum_logprob, tokens):
        score = sum_logprob / (max(len(tokens), 1) ** self.lp)
        self.items.append((score, tokens))
        self.items.sort(key=lambda t: -t[0])
        del self.items[self.k:]

    def is_done(self, best_running_sum, cur_len):
        if len(self.items) < self.k:
            return False
        if self.early:
            return True
        return (best_running_sum / (max(cur_len, 1) ** self.lp)
                <= self.items[-1][0])


def _beam_search(model, last, caches, max_len, max_new_tokens,
                 num_beams, eos_token_id, length_penalty, early_stopping,
                 rp=1.0, histories0=None, min_new=0, ngram=0):
    """Host-scored beam search over the cached decode path (the LLM analog
    of nn.BeamSearchDecoder/dynamic_decode; HF generate num_beams
    semantics). ``last``/``caches`` arrive from the B-row prefill; beams
    live as B*K cache rows, reordered inside the jitted _BeamStep."""
    import numpy as np

    B = last.shape[0]
    caches = jax.tree.map(
        lambda a: jnp.repeat(a, num_beams, axis=0) if _rows_match(a, B)
        else a, caches)
    step_fn = _get_beam_step(model, max_len)
    holder = {"caches": caches}

    def step(token, row_idx):
        logp, holder["caches"] = step_fn(token, jnp.asarray(row_idx),
                                         holder["caches"])
        # beam scoring runs on host by design: ONE fetch per beam step
        return np.asarray(logp)  # pdlint: disable=host-sync

    logp0 = np.asarray(jax.nn.log_softmax(last.astype(jnp.float32), axis=-1))
    arr = beam_search_loop(logp0, step, max_new_tokens, num_beams,
                           eos_token_id, length_penalty, early_stopping,
                           rp=rp, histories0=histories0, min_new=min_new,
                           ngram=ngram)
    return wrap(jnp.asarray(arr))


def beam_search_loop(logp0, step, max_new_tokens, num_beams, eos_token_id,
                     length_penalty, early_stopping, rp=1.0, histories0=None,
                     min_new=0, ngram=0):
    """The host scoring loop of beam search, decoupled from the model: a
    caller supplies ``logp0`` (np [B, V] log-probs of the first position)
    and ``step(token [B*K, 1] jnp, row_idx [B*K] np) -> np [B*K, V]``
    log-probs of the next position, with beam-origin cache reordering the
    step's own responsibility. Serves the decoder-only path and the
    encoder-decoder families (T5/BART num_beams). Returns np [B, width]."""
    import numpy as np

    B, V = logp0.shape
    K = num_beams
    logp0 = np.repeat(logp0, K, axis=0).reshape(B, K, V)
    # beam 0 seeds the search; the copies start at -inf so step 1's top-k
    # cannot pick the same token K times
    cum = np.full((B, K), -np.inf, np.float64)
    cum[:, 0] = 0.0
    hyps = [_BeamHyps(K, length_penalty, early_stopping) for _ in range(B)]
    done = [False] * B
    beams_tokens = [[[] for _ in range(K)] for _ in range(B)]
    logp = logp0

    # prompt n-gram maps built ONCE per batch row: per-step beam work then
    # hashes only the short generated tail (+ the boundary n-grams via the
    # prompt's last n-1 tokens), not the whole prompt again — the greedy
    # path's _NgramBan amortization, adapted to beam reordering
    base_maps = ([_NgramBan([h], ngram) for h in histories0]
                 if (ngram and histories0 is not None) else None)
    prompt_sets = ([set(h) for h in histories0]
                   if (rp != 1.0 and histories0 is not None) else None)

    def _process(scores, step_i):
        """HF beam-search processor order on the [B, K, V] scores."""
        eos_active = bool(min_new and eos_token_id is not None
                          and step_i < min_new)
        if (histories0 is None and not eos_active) or all(done):
            return scores
        out = np.array(scores, np.float64)
        for b in range(B):
            if done[b] or histories0 is None:
                continue
            prompt = histories0[b]
            tail = prompt[-(ngram - 1):] if ngram > 1 else []
            for j in range(K):
                gen = beams_tokens[b][j]
                row = out[b, j]
                if rp != 1.0 and (prompt or gen):
                    idx = np.fromiter(prompt_sets[b] | set(gen), np.int64)
                    vals = row[idx]
                    row[idx] = np.where(vals < 0, vals * rp, vals / rp)
                if ngram:
                    hist = prompt + gen
                    prefix = (tuple(hist[-(ngram - 1):]) if ngram > 1
                              else ())
                    banned = set(base_maps[b].maps[0].get(prefix, ()))
                    banned |= _NgramBan([tail + gen], ngram).maps[0].get(
                        prefix, set())
                    if banned:
                        row[list(banned)] = -np.inf
        if eos_active:
            out[:, :, eos_token_id] = -np.inf
        return out

    for i in range(max_new_tokens):
        logp_p = _process(logp, i)
        total = cum[:, :, None] + logp_p        # [B, K, V] float64 scores
        flat = total.reshape(B, K * V)
        # 2K candidates per batch (eos hits may retire, HF convention);
        # O(KV) partial select, then sort only the survivors
        part = np.argpartition(-flat, 2 * K - 1, axis=1)[:, : 2 * K]
        order = np.argsort(-np.take_along_axis(flat, part, axis=1), axis=1)
        top = np.take_along_axis(part, order, axis=1)
        next_tokens = []
        next_origin = []
        next_cum = []
        for b in range(B):
            if done[b]:
                next_tokens.append([0] * K)
                next_origin.append([b * K] * K)
                next_cum.append([-np.inf] * K)
                continue
            toks, orig, cums = [], [], []
            for rank, cand in enumerate(top[b]):
                beam, tok = divmod(int(cand), V)
                score = flat[b, cand]
                if eos_token_id is not None and tok == eos_token_id:
                    if rank < K:  # only top-K eos candidates retire
                        hyps[b].add(score, beams_tokens[b][beam] + [tok])
                    continue
                toks.append(tok)
                orig.append(b * K + beam)
                cums.append(score)
                if len(toks) == K:
                    break
            next_tokens.append(toks)
            next_origin.append(orig)
            next_cum.append(cums)
            beams_tokens[b] = [beams_tokens[b][orig[j] - b * K] +
                               [toks[j]] for j in range(K)]
            # HF passes the max over ALL 2K candidates (eos hits included)
            # as the best running sum — not just the kept non-eos beams
            if hyps[b].is_done(float(flat[b, top[b][0]]), i + 1):
                done[b] = True
        if all(done) or i == max_new_tokens - 1:
            for b in range(B):
                if not done[b] or not hyps[b].items:
                    # flush running beams at the length limit
                    for j in range(K):
                        if np.isfinite(next_cum[b][j]):
                            hyps[b].add(next_cum[b][j], beams_tokens[b][j])
            break
        cum = np.asarray(next_cum, np.float64)
        row_idx = np.asarray(next_origin, np.int32).reshape(-1)
        token = jnp.asarray(np.asarray(next_tokens, np.int64).reshape(-1, 1))
        logp = step(token, row_idx).reshape(B, K, V)

    outs = []
    for b in range(B):
        if hyps[b].items:
            outs.append(hyps[b].items[0][1])
        else:  # no finished hypothesis: best running beam
            outs.append(beams_tokens[b][int(np.argmax(cum[b]))])
    width = max(1, max(len(o) for o in outs))
    fill = eos_token_id if eos_token_id is not None else 0
    arr = np.full((B, width), fill, np.int64)
    for b, o in enumerate(outs):
        arr[b, : len(o)] = o
    return arr


class _PrefillStep:
    """ONE jitted computation for the whole prefill: empty caches → all
    layers (flash kernel over the prompt — cache `pos` is a concrete 0
    inside the trace, so the fast path survives jit) → each row's last real
    logit. Eager prefill costs one device dispatch per op per layer; this is
    the serving path's second half of the TrainStep pattern."""

    def __init__(self, model, max_len, ragged, rope_len=None,
                 embeds_input=False):
        # rope_len decouples the cos/sin table length from the cache
        # length: the serving engine prefills into a BUCKET-sized cache but
        # provisions rope at its max_len, so length-keyed rope regimes
        # (Phi-3 longrope short/long factors) match its decode program.
        # embeds_input: the first call argument is pre-merged embeddings
        # (multimodal admission) instead of token ids.
        rope_len = max_len if rope_len is None else rope_len
        self._model = model

        def pure(state, ids_or_embeds, lengths, pad_mask):
            with _functional_weights(model, state), _tape.no_grad():
                B = ids_or_embeds.shape[0]
                caches = _empty_caches(
                    model, B, max_len,
                    allowed=pad_mask if ragged else None)
                if embeds_input:
                    hidden, caches = model.llama.forward_cached(
                        None, caches, rope_len=rope_len,
                        inputs_embeds=wrap(ids_or_embeds))
                else:
                    hidden, caches = model.llama.forward_cached(
                        wrap(ids_or_embeds), caches, rope_len=rope_len)
                h_last = jnp.take_along_axis(
                    unwrap(hidden),
                    (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)
                last = unwrap(model.lm_head_logits(wrap(h_last)))[:, 0, :]
            return last, _unwrap_caches(caches)

        self._jitted = jax.jit(pure)
        self._state = dict(model.functional_state())

    def __call__(self, ids, lengths, pad_mask):
        return self._jitted(self._state, ids, lengths, pad_mask)


def _trace_flags_key() -> tuple:
    """The trace-relevant flag values as seen by THIS thread (including
    any thread-local overlay). Folded into every step-memoization key:
    flags are read at trace time, so a cached executable is only valid
    for the flag values it was traced under — a flag flip (or an audit
    thread's flag_overrides) must get its own program, not silently
    reuse one traced the other way."""
    from .utils.flags import flag

    return (bool(flag("FLAGS_use_fused_decode_tail")),)


def _memoized_step(model, attr, key, factory, maxsize=None):
    """Per-model step memoization: jax.jit's compile cache keys on the
    function object, so a fresh step per generate() call would recompile
    every request (review finding). On a hit, the step re-reads the model's
    CURRENT weights. ``maxsize`` evicts the LEAST-RECENTLY-USED entry for
    caches whose key space is unbounded (per-request lengths): a hit
    re-inserts its key at the back, so a working set that cycles through
    many keys per request (the chunked-prefill suffix programs) keeps its
    hot programs instead of evicting in insertion order.

    Keys are extended with the trace-relevant flag fingerprint
    (:func:`_trace_flags_key`) so programs traced under different flag
    values never alias."""
    key = (key, _trace_flags_key())
    cache = model.__dict__.get(attr)
    if cache is None:
        cache = {}
        object.__setattr__(model, attr, cache)
    step = cache.get(key)
    if step is None:
        step = factory()
        if maxsize is not None and len(cache) >= maxsize:
            cache.pop(next(iter(cache)))
        cache[key] = step
    else:
        if maxsize is not None:
            cache.pop(key)
            cache[key] = step
        step._state = dict(model.functional_state())
    return step


def _get_prefill_step_embeds(model, max_len, ragged, rope_len=None):
    """Multimodal prefill: same jitted computation as _get_prefill_step,
    but the first argument is PRE-MERGED embeddings (LLaVA image features
    already scattered into the prompt) instead of token ids."""
    return _memoized_step(model, "_prefill_steps_embeds",
                          (max_len, ragged, rope_len),
                          lambda: _PrefillStep(model, max_len, ragged,
                                               rope_len=rope_len,
                                               embeds_input=True),
                          maxsize=16)


def _get_prefill_step(model, max_len, ragged, rope_len=None):
    # max_len varies per request: bound the cache (oldest-evicted)
    return _memoized_step(model, "_prefill_steps",
                          (max_len, ragged, rope_len),
                          lambda: _PrefillStep(model, max_len, ragged,
                                               rope_len=rope_len),
                          maxsize=16)


class _ChunkedPrefillStep:
    """Prefill as ONE jitted ``lax.scan`` over fixed-size prompt chunks
    (vLLM-style chunked prefill, TPU-shaped): compile cost scales with the
    CHUNK COUNT bucket instead of one compile per prompt-shape, and the
    per-layer MLP/projection activations are one chunk's worth. Chunk c
    writes cache entries [cC, cC+C) and attends to every earlier entry
    through the cache's pos/column masking, so the result is exactly the
    one-shot prefill. The running last-real-hidden is carried so only a
    [B, H] gather (not the full prompt's hidden) leaves the loop.

    Cost model: on TPU each chunk's attention runs the Pallas
    append-attention kernel (ops/pallas/append_attention.py — streaming
    softmax over the buffer, traced ``pos`` via scalar prefetch, KV
    blocks beyond pos+S skipped), so compute scales with the VALID
    prefix: total O(S^2/2) like a causal kernel. Where the kernel's gate
    declines (CPU, untileable dims, KV beyond its VMEM budget), the
    dense fallback materializes f32 scores [B, kv_heads, group, C,
    max_len] per layer and attends the whole buffer — pick C so
    C x max_len stays modest there."""

    def __init__(self, model, max_len, chunk, n_chunks):
        self._model = model
        C, n = int(chunk), int(n_chunks)

        def pure(state, ids_pad, lengths, allowed):
            B = ids_pad.shape[0]
            with _functional_weights(model, state), _tape.no_grad():
                caches = _empty_caches(model, B, max_len, allowed=allowed)
                for c in caches:
                    # scan-stable carry: pos as a traced scalar, no static
                    # "prefill" marker (its dict entry would be dropped by
                    # the first step and change the carry structure)
                    c.pop("prefill", None)
                    c["pos"] = jnp.asarray(0, jnp.int32)
                bufs, aux = _split_caches(caches)
                chunks = ids_pad.reshape(B, n, C).transpose(1, 0, 2)

                def body(carry, chunk_ids):
                    bufs, aux, h_last, start = carry
                    cs = [{**b, **a} for b, a in zip(bufs, aux)]
                    hidden, cs = model.llama.forward_cached(
                        wrap(chunk_ids), cs, rope_len=max_len)
                    h = unwrap(hidden)
                    idx = lengths.astype(jnp.int32) - 1 - start
                    in_chunk = (idx >= 0) & (idx < C)
                    picked = jnp.take_along_axis(
                        h, jnp.clip(idx, 0, C - 1)[:, None, None], axis=1
                    )[:, 0]
                    h_last = jnp.where(in_chunk[:, None], picked, h_last)
                    nb, na = _split_caches(_unwrap_caches(cs))
                    return (nb, na, h_last, start + C), None

                h0 = jnp.zeros((B, model.config.hidden_size),
                               jnp.dtype(model.config.dtype)
                               if isinstance(model.config.dtype, str)
                               else model.config.dtype)
                (bufs, aux, h_last, _), _ = jax.lax.scan(
                    body, (bufs, aux, h0, jnp.asarray(0, jnp.int32)), chunks)
                last = unwrap(model.lm_head_logits(
                    wrap(h_last[:, None, :])))[:, 0, :]
            return last, bufs, aux

        self._jitted = jax.jit(pure)
        self._state = dict(model.functional_state())

    def __call__(self, ids_pad, lengths, allowed):
        last, bufs, aux = self._jitted(self._state, ids_pad, lengths, allowed)
        return last, [{**b, **a} for b, a in zip(bufs, aux)]


def _get_chunked_prefill_step(model, max_len, chunk, n_chunks):
    return _memoized_step(
        model, "_chunked_prefill_steps", (max_len, chunk, n_chunks),
        lambda: _ChunkedPrefillStep(model, max_len, chunk, n_chunks),
        maxsize=8)


def _sample_and_forward(model, max_len, last, key, bufs, aux,
                        do_sample, temperature, top_k, top_p, sampler=None):
    """The fused per-token unit shared by the scan decode and the engine
    step: sample from ``last``, run one cached forward, return
    (token, chosen-token logprob, next logits, split caches). The logprob
    is under the model's RAW distribution over ``last`` (the OpenAI
    "logprobs" field — one fused log_softmax gather while the logits are
    in hand). Caller provides the weight context (functional_weights) and
    the RNG key; ``sampler`` overrides the scalar sample_logits call (the
    per-row engine path)."""
    if sampler is not None:
        nxt = sampler(last, key)
    else:
        nxt = sample_logits(last, key, do_sample=do_sample,
                            temperature=temperature, top_k=top_k, top_p=top_p)
    lp = jax.nn.log_softmax(last.astype(jnp.float32), -1)[
        jnp.arange(last.shape[0]), nxt]
    token = nxt[:, None].astype(jnp.int32)
    caches = [{**b, **a} for b, a in zip(bufs, aux)]
    with _tape.no_grad():
        hidden, new_caches = model.llama.forward_cached(
            wrap(token), caches, rope_len=max_len)
        logits = model.lm_head_logits(hidden)
    nb, na = _split_caches(_unwrap_caches(new_caches))
    return nxt, lp, unwrap(logits)[:, -1, :], nb, na


class _ScanDecodeStep:
    """The WHOLE decode loop as one jitted ``lax.scan``: each step samples
    the next token from the carried logits, runs one cached forward, and
    carries the updated (donated) KV buffers. One device dispatch for the
    entire generation instead of two per token — the python loop remains
    only for eos early-stopping (data-dependent length needs host control).
    """

    def __init__(self, model, max_len, steps, do_sample, temperature,
                 top_k, top_p):
        self._model = model

        def pure(state, last, base_key, bufs, aux):
            with _functional_weights(model, state):
                def body(carry, t):
                    last_t, bufs_t, aux_t = carry
                    key = jax.random.fold_in(base_key, t)
                    nxt, _lp, last_n, nb, na = _sample_and_forward(
                        model, max_len, last_t, key, bufs_t, aux_t,
                        do_sample, temperature, top_k, top_p)
                    return (last_n, nb, na), nxt

                (last_f, bufs_f, aux_f), toks = jax.lax.scan(
                    body, (last, bufs, aux), jnp.arange(steps))
            return toks, last_f, bufs_f, aux_f

        self._jitted = jax.jit(pure, donate_argnums=(3,))
        self._state = dict(model.functional_state())

    def __call__(self, last, base_key, caches):
        bufs, aux = _split_caches(caches)
        # scan carries must be type-stable across iterations: normalize the
        # python-int pos (static after prefill; absent in paged caches,
        # which track per-row lengths instead) to a traced-compatible array
        aux = [dict(a, **({"pos": jnp.asarray(a["pos"], jnp.int32)}
                          if "pos" in a else {})) for a in aux]
        toks, last_f, nb, na = self._jitted(self._state, last, base_key,
                                            bufs, aux)
        return toks, last_f, [{**b, **a} for b, a in zip(nb, na)]


class _SelectDecodeStep:
    """sample + one cached forward fused into ONE jitted dispatch: the
    continuous-batching engine's per-step unit (the scan variant without
    the scan — the host must see each token for slot retirement)."""

    def __init__(self, model, max_len, do_sample, temperature, top_k, top_p):
        self._model = model

        def pure(state, last, key, bufs, aux):
            with _functional_weights(model, state):
                nxt, lp, last_n, nb, na = _sample_and_forward(
                    model, max_len, last, key, bufs, aux,
                    do_sample, temperature, top_k, top_p)
            return nxt, lp, last_n.astype(jnp.float32), nb, na

        self._jitted = jax.jit(pure, donate_argnums=(3,))
        self._state = dict(model.functional_state())

    def __call__(self, last, key, caches):
        bufs, aux = _split_caches(caches)
        nxt, lp, last_f, nb, na = self._jitted(self._state, last, key,
                                               bufs, aux)
        return nxt, lp, last_f, [{**b, **a} for b, a in zip(nb, na)]


class _SelectDecodeRowsStep:
    """_SelectDecodeStep with PER-ROW sampling parameters as traced args:
    one compiled program serves any per-request greedy/temperature/top-k/
    top-p mix in the continuous-batching engine."""

    def __init__(self, model, max_len):
        self._model = model

        def pure(state, last, key, do_s, temp, tk, tp, bufs, aux):
            with _functional_weights(model, state):
                nxt, lp, last_n, nb, na = _sample_and_forward(
                    model, max_len, last, key, bufs, aux,
                    None, None, None, None,
                    sampler=lambda lg, k: sample_logits_rows(
                        lg, k, do_s, temp, tk, tp))
            return nxt, lp, last_n.astype(jnp.float32), nb, na

        self._jitted = jax.jit(pure, donate_argnums=(7,))
        self._state = dict(model.functional_state())

    def __call__(self, last, key, do_s, temp, tk, tp, caches):
        bufs, aux = _split_caches(caches)
        nxt, lp, last_f, nb, na = self._jitted(self._state, last, key,
                                               do_s, temp, tk, tp, bufs,
                                               aux)
        return nxt, lp, last_f, [{**b, **a} for b, a in zip(nb, na)]


class _SpecDecodeStep:
    """Greedy speculative decode unit for the continuous-batching engine,
    ONE jitted dispatch per round: argmax the carried logits (the token a
    plain step would emit), forward a k-token chunk [g0, d_1..d_{k-1}] of
    host-proposed draft tokens through the paged cache at per-row
    positions, and compute the longest target-greedy-consistent accepted
    run on device. Returns everything the engine's host loop needs in one
    fetch: the emitted-token matrix, per-row emit counts, per-token
    logprobs (raw distribution — the OpenAI logprobs field), and the
    logits row that seeds the next round.

    Token-identity is by construction: position 0 always forwards g0
    (the verified greedy token), and draft j is emitted only when it
    EQUALS the target's greedy choice at its position — junk drafts can
    only be accepted when they happen to match the true token, so
    acceptance changes latency, never output. Rejected-suffix KV parks
    above the post-accept frontier (see paged_cached_attention)."""

    def __init__(self, model, max_len, k):
        self._model = model
        k = int(k)

        def pure(state, last, drafts, bufs, aux):
            B = last.shape[0]
            with _functional_weights(model, state), _tape.no_grad():
                g0 = jnp.argmax(last, axis=-1).astype(jnp.int32)   # [B]
                chunk = (jnp.concatenate([g0[:, None], drafts], axis=1)
                         if k > 1 else g0[:, None])                # [B,k]
                caches = [{**b, **a} for b, a in zip(bufs, aux)]
                hidden, new_caches = model.llama.forward_cached(
                    wrap(chunk), caches, rope_len=max_len)
                logits = unwrap(model.lm_head_logits(hidden)
                                ).astype(jnp.float32)              # [B,k,V]
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B,k]
            if k > 1:
                ok = (drafts == greedy[:, :-1]).astype(jnp.int32)  # [B,k-1]
                n_acc = jnp.cumprod(ok, axis=1).sum(axis=1)        # [B]
            else:
                n_acc = jnp.zeros((B,), jnp.int32)
            # logits after the LAST emitted token (chunk position n_acc)
            # seed the next round — the bonus token is next round's g0
            new_last = jnp.take_along_axis(
                logits, n_acc[:, None, None].astype(jnp.int32), axis=1
            )[:, 0]                                                 # [B,V]
            lp0 = jax.nn.log_softmax(last.astype(jnp.float32), -1)[
                jnp.arange(B), g0]                                  # [B]
            if k > 1:
                lpd = jnp.take_along_axis(
                    jax.nn.log_softmax(logits[:, :-1], -1),
                    drafts[:, :, None].astype(jnp.int32), axis=2
                )[:, :, 0]                                          # [B,k-1]
                lps = jnp.concatenate([lp0[:, None], lpd], axis=1)
            else:
                lps = lp0[:, None]
            nb, na = _split_caches(_unwrap_caches(new_caches))
            return chunk, n_acc + 1, lps, new_last, nb, na

        self._jitted = jax.jit(pure, donate_argnums=(3,))
        self._state = dict(model.functional_state())

    def __call__(self, last, drafts, caches):
        bufs, aux = _split_caches(caches)
        toks, n_emit, lps, last_f, nb, na = self._jitted(
            self._state, last, drafts, bufs, aux)
        return toks, n_emit, lps, last_f, [{**b, **a}
                                           for b, a in zip(nb, na)]


def _get_spec_decode(model, max_len, k):
    return _memoized_step(
        model, "_spec_decode_steps", (max_len, int(k)),
        lambda: _SpecDecodeStep(model, max_len, k), maxsize=8)


def _get_select_decode_rows(model, max_len):
    return _memoized_step(
        model, "_select_decode_rows_steps", (max_len,),
        lambda: _SelectDecodeRowsStep(model, max_len))


def _get_select_decode(model, max_len, do_sample, temperature, top_k, top_p):
    key = (max_len, do_sample, float(temperature), int(top_k), float(top_p))
    return _memoized_step(
        model, "_select_decode_steps", key,
        lambda: _SelectDecodeStep(model, max_len, do_sample,
                                  float(temperature), int(top_k),
                                  float(top_p)))


def _get_scan_decode(model, max_len, steps, do_sample, temperature, top_k,
                     top_p):
    # NOTE: keyed on the request's exact step count — a serving mix of many
    # distinct max_new_tokens values compiles one scan program each (the
    # fixed-length-batch assumption of this fast path). The cache is
    # LRU-bounded so varied lengths cannot accumulate executables forever.
    key = (max_len, steps, do_sample, float(temperature), int(top_k),
           float(top_p))
    return _memoized_step(
        model, "_scan_decode_steps", key,
        lambda: _ScanDecodeStep(model, max_len, steps, do_sample,
                                float(temperature), int(top_k),
                                float(top_p)),
        maxsize=16)


def _get_decode_step(model, max_len):
    return _memoized_step(model, "_decode_steps", max_len,
                          lambda: _DecodeStep(model, max_len))


# ---------------------------------------------------------------------------
# generate
# ---------------------------------------------------------------------------

#: defaults of the decoder-only generate() below — encoder-decoder
#: families (T5/BART) accept these kwargs when passed AT their default
#: (callers using the generic signature must not break on explicit
#: defaults, ADVICE r4) and raise only on a genuinely different value
GENERATE_DEFAULTS = {
    "use_cache": True, "paged": False, "page_size": 16,
    "prefill_chunk_size": None, "repetition_penalty": 1.0,
    "min_new_tokens": 0, "num_beams": 1, "length_penalty": 1.0,
    "early_stopping": False, "no_repeat_ngram_size": 0,
}


def reject_non_default_kwargs(family: str, kwargs: dict):
    """Raise for unsupported generate() kwargs UNLESS the caller passed
    the shared default value explicitly."""
    for k, v in kwargs.items():
        if k in GENERATE_DEFAULTS and v == GENERATE_DEFAULTS[k]:
            continue
        raise NotImplementedError(
            f"{family}.generate does not support {k}={v!r} (decoder-only "
            "families carry the full strategy surface)")


def generate(model, input_ids, max_new_tokens=20, do_sample=False,
             temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
             use_cache=True, attention_mask=None, paged=False,
             page_size=16, prefill_chunk_size=None,
             repetition_penalty=1.0, min_new_tokens=0,
             num_beams=1, length_penalty=1.0, early_stopping=False,
             no_repeat_ngram_size=0):
    """Batched autoregressive decode.

    ``repetition_penalty`` (HF semantics): logits of tokens already in the
    row (prompt + generated so far) are divided by the penalty when
    positive, multiplied when negative. ``min_new_tokens`` blocks
    ``eos_token_id`` for the first N generated tokens (requires eos).
    ``no_repeat_ngram_size=n`` bans tokens that would repeat an n-gram of
    the row's sequence (prompt + generated).

    ``num_beams > 1`` runs beam search (greedy scoring over K beams per
    row, HF semantics: 2K candidates per step, eos hits retire into a
    hypothesis pool scored by sum-logprob / len**length_penalty); returns
    each row's best hypothesis.

    ``attention_mask`` [B, S0] (1 = real token; right- OR
    left-padded rows — HF tokenizer output works directly, left pads
    roll to the internal right-padded layout exactly) makes
    ragged batches correct: pad columns are never attended, RoPE positions
    continue per row from each row's true length, and the first sampled
    token reads each row's last real logit.

    ``prefill_chunk_size``: process the prompt as a ``lax.scan`` over
    fixed-size chunks (chunked prefill) — compile cost buckets by chunk
    COUNT instead of exact prompt shape, and prefill activation memory is
    one chunk's worth. Output is identical to the one-shot prefill.

    Returns generated ids [B, <=max_new_tokens] (prompt excluded); stops
    early only when EVERY row has emitted eos.
    """
    ids = unwrap(input_ids) if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    B, S0 = ids.shape
    cfg = model.config
    if max_new_tokens <= 0:
        return wrap(jnp.zeros((B, 0), ids.dtype))
    rp = float(repetition_penalty)
    if rp <= 0:
        raise ValueError("repetition_penalty must be positive")
    min_new = int(min_new_tokens)
    if min_new > 0 and eos_token_id is None:
        raise ValueError("min_new_tokens requires eos_token_id (it only "
                         "delays the eos stop)")
    ngram = int(no_repeat_ngram_size)
    penalized = rp != 1.0 or min_new > 0 or ngram > 0
    if paged and getattr(model.llama, "empty_cache_layer", None) is not None:
        # fail BEFORE the prefill: the paged layout needs per-head k/v
        # caches; MLA latent caches (c_kv/k_pe) decode dense-buffer only
        raise NotImplementedError(
            "the paged KV layout needs per-head k/v caches; MLA latent "
            "caches (c_kv/k_pe) decode through the dense buffer path "
            "(paged=False)")
    num_beams = int(num_beams)
    if num_beams > 1:
        if do_sample:
            raise NotImplementedError(
                "beam search with do_sample=True (beam sampling) is not "
                "supported; use num_beams>1 with do_sample=False")
        if paged:
            raise NotImplementedError(
                "beam search over the paged KV layout is not supported; "
                "use paged=False (beams reorder dense cache rows)")
        if not use_cache:
            raise NotImplementedError("beam search needs use_cache=True")
    chunk = int(prefill_chunk_size) if prefill_chunk_size else 0
    if chunk:
        if not use_cache:
            raise NotImplementedError(
                "prefill_chunk_size needs the cached path (use_cache=True)")
        n_chunks = -(-S0 // chunk)
        prompt_pad = n_chunks * chunk   # cache slots the padded prompt uses
    else:
        prompt_pad = S0
    max_len = prompt_pad + max_new_tokens
    if paged:
        max_len = -(-max_len // page_size) * page_size
    if max_len > cfg.max_position_embeddings:
        raise ValueError(
            f"generate: prompt+new tokens {max_len} exceeds "
            f"max_position_embeddings {cfg.max_position_embeddings}")

    pad_mask = None
    lengths = jnp.full((B,), S0, jnp.int32)
    if attention_mask is not None:
        if not use_cache:
            raise NotImplementedError(
                "generate(use_cache=False) ignores attention_mask; use the "
                "cached path for padded prompts")
        am = unwrap(attention_mask) if isinstance(attention_mask, Tensor) \
            else jnp.asarray(attention_mask)
        lengths = am.astype(jnp.int32).sum(1)
        # The internal layout is RIGHT-padded: RoPE positions, the cache
        # write layout, and the last-real-logit gather all assume each
        # row's real tokens are a CONTIGUOUS PREFIX. LEFT-padded prompts
        # (HF's generation convention) are accepted by rolling each row's
        # suffix to the front — generated tokens are pad-layout-invariant,
        # so this is exact. Interior holes still fail loudly.
        prefix = jnp.arange(S0)[None, :] < lengths[:, None]
        amb = am.astype(bool)
        if bool((amb != prefix).any()):
            suffix = jnp.arange(S0)[None, :] >= (S0 - lengths)[:, None]
            # PER-ROW gate: rows may mix right- and left-padded layouts
            # (each contiguous); only interior holes are invalid
            is_prefix = (amb == prefix).all(axis=1)
            is_suffix = (amb == suffix).all(axis=1)
            if not bool((is_prefix | is_suffix).all()):
                raise ValueError(
                    "generate(attention_mask=...) expects right- or "
                    "left-padded prompts (contiguous real tokens); got a "
                    "mask with interior holes.")
            # roll left-padded rows' suffix to the front (right-padded
            # rows shift by 0)
            shifts = jnp.where(is_prefix, 0, S0 - lengths)[:, None]
            idx = (jnp.arange(S0)[None, :] + shifts) % S0
            ids = jnp.take_along_axis(ids, idx, axis=1)
            am = jnp.take_along_axis(am, idx, axis=1)
        if bool((lengths < 1).any()):
            raise ValueError(
                "generate(attention_mask=...): every row needs at least one "
                "real token — an all-zero mask row would decode from a pad "
                "position's logits")
        pad_mask = jnp.concatenate(
            [am.astype(bool),
             jnp.ones((B, max_len - S0), bool)], axis=1)

    with _tape.no_grad():
        if not use_cache:
            return _generate_no_cache(model, ids, max_new_tokens, do_sample,
                                      temperature, top_k, top_p, eos_token_id,
                                      rp=rp, min_new=min_new, ngram=ngram)

        # ---- prefill: one jitted computation (flash kernel + cache fill +
        # last-real-logit gather; the [B,1,H] gather before the lm head
        # keeps the vocab projection S0x smaller in HBM) ----
        if chunk:
            if pad_mask is None and prompt_pad == S0:
                # evenly divisible unpadded prompt: pos masking suffices,
                # no column mask needed
                pass
            else:
                # chunked prompts are internally ragged: pad columns
                # between each row's true length and the padded prompt
                # region must never be attended, and decode RoPE continues
                # per row
                am_eff = (pad_mask[:, :S0] if pad_mask is not None
                          else jnp.ones((B, S0), bool))
                pad_mask = jnp.concatenate(
                    [am_eff, jnp.zeros((B, prompt_pad - S0), bool),
                     jnp.ones((B, max_len - prompt_pad), bool)], axis=1)
            ids_pad = jnp.concatenate(
                [ids, jnp.zeros((B, prompt_pad - S0), ids.dtype)], axis=1)
            prefill = _get_chunked_prefill_step(model, max_len, chunk,
                                                n_chunks)
            last, caches = prefill(ids_pad, lengths, pad_mask)
        else:
            prefill = _get_prefill_step(model, max_len, pad_mask is not None)
            last, caches = prefill(ids, lengths, pad_mask)

        if paged:
            caches = _caches_to_paged(caches, page_size, lengths, pad_mask)

        # per-row RoPE positions for the generated tokens (ragged batches
        # continue at each row's true length)
        if pad_mask is not None and not paged:
            for c in caches:
                c["row_pos"] = lengths

        if num_beams > 1:
            histories0 = None
            if rp != 1.0 or ngram > 0:
                ids_np = np.asarray(ids)
                lens_np = np.asarray(lengths)
                histories0 = [list(map(int, ids_np[b, : lens_np[b]]))
                              for b in range(B)]
            return _beam_search(model, last, caches, max_len,
                                max_new_tokens, num_beams, eos_token_id,
                                float(length_penalty), bool(early_stopping),
                                rp=rp, histories0=histories0,
                                min_new=min_new, ngram=ngram)

        if eos_token_id is None and max_new_tokens > 1 and not penalized:
            # fixed-length decode: the whole loop is ONE lax.scan dispatch
            # (sample_t → forward_t → logits_{t+1}); the final token needs
            # only a sample, no forward. (A repetition penalty carries a
            # [B, V] seen-set — that run takes the host loop below.)
            scan = _get_scan_decode(model, max_len, max_new_tokens - 1,
                                    do_sample, temperature, top_k, top_p)
            toks, last, caches = scan(last, _random.next_key(), caches)
            final = _select(last, _random.next_key(), do_sample,
                            float(temperature), int(top_k), float(top_p))
            return wrap(jnp.concatenate(
                [toks.T.astype(ids.dtype), final.reshape(B, 1).astype(ids.dtype)],
                axis=1))

        step = _get_decode_step(model, max_len)
        finished = jnp.zeros((B,), bool)
        seen = (_seen_from_prompt(ids, cfg.vocab_size, pad_mask)
                if rp != 1.0 else None)
        tracker = None
        if ngram > 0:
            ids_np = np.asarray(ids)
            lens_np = np.asarray(lengths)
            tracker = _NgramBan(
                [list(ids_np[b, : lens_np[b]]) for b in range(B)], ngram)
        out_tokens = []
        for i in range(max_new_tokens):
            key = _random.next_key()
            if tracker is not None:
                banned = tracker.banned(cfg.vocab_size)
                if banned.any():  # skip the transfer on no-op steps
                    last = jnp.where(jnp.asarray(banned), -jnp.inf,
                                     last.astype(jnp.float32))
            nxt = _select_next(last, seen, key, do_sample, temperature,
                               top_k, top_p, rp, i, min_new, eos_token_id)
            if eos_token_id is not None:
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            if seen is not None:
                seen = seen.at[jnp.arange(B), nxt].set(True)
            if tracker is not None:
                for b, t in enumerate(np.asarray(nxt)):
                    tracker.append(b, int(t))
            out_tokens.append(nxt.reshape(B, 1).astype(ids.dtype))
            if i == max_new_tokens - 1 or (
                    eos_token_id is not None and bool(finished.all())):
                break
            logits, caches = step(out_tokens[-1], caches)
            last = logits[:, -1, :]
        return wrap(jnp.concatenate(out_tokens, axis=1))


def _generate_no_cache(model, ids, max_new_tokens, do_sample, temperature,
                       top_k, top_p, eos_token_id, rp=1.0, min_new=0,
                       ngram=0):
    B = ids.shape[0]
    finished = jnp.zeros((B,), bool)
    seen = (_seen_from_prompt(ids, model.config.vocab_size)
            if rp != 1.0 else None)
    tracker = (_NgramBan([list(np.asarray(ids)[b]) for b in range(B)], ngram)
               if ngram > 0 else None)
    out_tokens = []
    full = ids
    for i in range(max_new_tokens):
        hidden = model.llama(wrap(full))
        last = unwrap(model.lm_head_logits(hidden))[:, -1, :]
        key = _random.next_key()
        if tracker is not None:
            banned = tracker.banned(model.config.vocab_size)
            if banned.any():
                last = jnp.where(jnp.asarray(banned), -jnp.inf,
                                 last.astype(jnp.float32))
        nxt = _select_next(last, seen, key, do_sample, temperature, top_k,
                           top_p, rp, i, min_new, eos_token_id)
        if eos_token_id is not None:
            nxt = jnp.where(finished, eos_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        if seen is not None:
            seen = seen.at[jnp.arange(B), nxt].set(True)
        if tracker is not None:
            for b, t in enumerate(np.asarray(nxt)):
                tracker.append(b, int(t))
        out_tokens.append(nxt.reshape(B, 1).astype(ids.dtype))
        full = jnp.concatenate([full, out_tokens[-1]], axis=1)
        if eos_token_id is not None and bool(finished.all()):
            break
    return wrap(jnp.concatenate(out_tokens, axis=1))


# ---------------------------------------------------------------------------
# paged cache construction
# ---------------------------------------------------------------------------

def _caches_to_paged(caches, page_size, lengths, pad_mask):
    """Re-lay dense prefilled buffers [B, max_len, hk, D] into paged dicts
    (contiguous page tables; an allocator would virtualize page_indices)."""
    k0 = caches[0]["k"]
    B, max_len, hk, D = k0.shape
    pages_per_seq = max_len // page_size

    def to_pages(buf):
        p = buf.reshape(B, pages_per_seq, page_size, hk, D)
        return jnp.moveaxis(p, 3, 0).reshape(hk, B * pages_per_seq,
                                             page_size, D)

    page_indices = jnp.arange(B * pages_per_seq, dtype=jnp.int32).reshape(
        B, pages_per_seq)
    out = []
    for c in caches:
        out.append({
            "k_pages": to_pages(c["k"]),
            "v_pages": to_pages(c["v"]),
            "page_indices": page_indices,
            # per-row valid-token counts: paged_decode_attention masks by
            # position < lengths[b], and each decode step writes row b's
            # token at its own page/slot (lengths[b]) — right-pad garbage
            # sits at positions >= lengths[b] until overwritten, never
            # attended. Fully ragged batches are first-class.
            "lengths": lengths,
            "page_size": page_size,
        })
    return out


def generate_paged(model, input_ids, max_new_tokens=20, page_size=16,
                   **kwargs):
    """Paged-KV decode (block_multi_head_attention serving configuration):
    generate() with the paged cache layout."""
    return generate(model, input_ids, max_new_tokens=max_new_tokens,
                    paged=True, page_size=page_size, **kwargs)
