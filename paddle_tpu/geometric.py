"""paddle.geometric parity (python/paddle/geometric/): graph message
passing + segment ops, built on jax segment reductions (TPU-friendly
scatter lowering)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops.registry import apply

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _segments(ids, n=None):
    return int(n) if n is not None else None


def _concrete_num_segments(s):
    """Eager: max(ids)+1 (the reference's data-dependent output size).
    Under jit tracing that size cannot be data-dependent on TPU (static
    shapes) — raise with the workaround instead of silently mis-sizing."""
    if isinstance(s, jax.core.Tracer):
        raise ValueError(
            "paddle.geometric.segment_* output size is data-dependent "
            "(max(ids)+1) and cannot be traced under jit; call eagerly, or "
            "use send_u_recv(..., out_size=N) which has a static size")
    return int(jax.device_get(s).max()) + 1


def segment_sum(data, segment_ids, name=None):
    def fn(d, s):
        return jax.ops.segment_sum(d, s, num_segments=_concrete_num_segments(s))

    return apply("segment_sum", fn, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    def fn(d, s):
        n = _concrete_num_segments(s)
        tot = jax.ops.segment_sum(d, s, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(s, d.dtype), s, num_segments=n)
        shape = (-1,) + (1,) * (d.ndim - 1)
        return tot / jnp.maximum(cnt, 1).reshape(shape)

    return apply("segment_mean", fn, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    def fn(d, s):
        return jax.ops.segment_max(d, s, num_segments=_concrete_num_segments(s))

    return apply("segment_max", fn, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    def fn(d, s):
        return jax.ops.segment_min(d, s, num_segments=_concrete_num_segments(s))

    return apply("segment_min", fn, data, segment_ids)


_POOLS = {"sum": jax.ops.segment_sum, "mean": None, "max": jax.ops.segment_max,
          "min": jax.ops.segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """geometric/message_passing/send_recv.py parity: gather x[src], reduce
    at dst."""

    def fn(xv, src, dst):
        n = int(out_size) if out_size is not None else xv.shape[0]
        msgs = xv[src]
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, xv.dtype), dst,
                                      num_segments=n)
            return tot / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (xv.ndim - 1))
        return _POOLS[reduce_op](msgs, dst, num_segments=n)

    return apply("send_u_recv", fn, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Edge-feature variant: combine x[src] with edge feature y."""

    def fn(xv, yv, src, dst):
        n = int(out_size) if out_size is not None else xv.shape[0]
        m = xv[src]
        msgs = {"add": m + yv, "sub": m - yv, "mul": m * yv,
                "div": m / yv}[message_op]
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, xv.dtype), dst,
                                      num_segments=n)
            return tot / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (xv.ndim - 1))
        return _POOLS[reduce_op](msgs, dst, num_segments=n)

    return apply("send_ue_recv", fn, x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    def fn(xv, yv, src, dst):
        a, b = xv[src], yv[dst]
        return {"add": a + b, "sub": a - b, "mul": a * b,
                "div": a / b}[message_op]

    return apply("send_uv", fn, x, y, src_index, dst_index)
