"""paddle.geometric parity (python/paddle/geometric/): graph message
passing + segment ops, built on jax segment reductions (TPU-friendly
scatter lowering)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops.registry import apply

__all__ = ["reindex_graph", "reindex_heter_graph", "sample_neighbors",
           "weighted_sample_neighbors",
           "segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _segments(ids, n=None):
    return int(n) if n is not None else None


def _concrete_num_segments(s):
    """Eager: max(ids)+1 (the reference's data-dependent output size).
    Under jit tracing that size cannot be data-dependent on TPU (static
    shapes) — raise with the workaround instead of silently mis-sizing."""
    if isinstance(s, jax.core.Tracer):
        raise ValueError(
            "paddle.geometric.segment_* output size is data-dependent "
            "(max(ids)+1) and cannot be traced under jit; call eagerly, or "
            "use send_u_recv(..., out_size=N) which has a static size")
    return int(jax.device_get(s).max()) + 1


def segment_sum(data, segment_ids, name=None):
    def fn(d, s):
        return jax.ops.segment_sum(d, s, num_segments=_concrete_num_segments(s))

    return apply("segment_sum", fn, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    def fn(d, s):
        n = _concrete_num_segments(s)
        tot = jax.ops.segment_sum(d, s, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(s, d.dtype), s, num_segments=n)
        shape = (-1,) + (1,) * (d.ndim - 1)
        return tot / jnp.maximum(cnt, 1).reshape(shape)

    return apply("segment_mean", fn, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    def fn(d, s):
        return jax.ops.segment_max(d, s, num_segments=_concrete_num_segments(s))

    return apply("segment_max", fn, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    def fn(d, s):
        return jax.ops.segment_min(d, s, num_segments=_concrete_num_segments(s))

    return apply("segment_min", fn, data, segment_ids)


_POOLS = {"sum": jax.ops.segment_sum, "mean": None, "max": jax.ops.segment_max,
          "min": jax.ops.segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """geometric/message_passing/send_recv.py parity: gather x[src], reduce
    at dst."""

    def fn(xv, src, dst):
        n = int(out_size) if out_size is not None else xv.shape[0]
        msgs = xv[src]
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, xv.dtype), dst,
                                      num_segments=n)
            return tot / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (xv.ndim - 1))
        return _POOLS[reduce_op](msgs, dst, num_segments=n)

    return apply("send_u_recv", fn, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Edge-feature variant: combine x[src] with edge feature y."""

    def fn(xv, yv, src, dst):
        n = int(out_size) if out_size is not None else xv.shape[0]
        m = xv[src]
        msgs = {"add": m + yv, "sub": m - yv, "mul": m * yv,
                "div": m / yv}[message_op]
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, xv.dtype), dst,
                                      num_segments=n)
            return tot / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (xv.ndim - 1))
        return _POOLS[reduce_op](msgs, dst, num_segments=n)

    return apply("send_ue_recv", fn, x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    def fn(xv, yv, src, dst):
        a, b = xv[src], yv[dst]
        return {"add": a + b, "sub": a - b, "mul": a * b,
                "div": a / b}[message_op]

    return apply("send_uv", fn, x, y, src_index, dst_index)


def _np_of(t):
    import numpy as np

    from .tensor_class import unwrap

    return np.asarray(unwrap(t))


def _host_rng():
    from .framework.random import host_rng

    return host_rng()


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """paddle.geometric.reindex_graph (geometric/reindex.py; graph_reindex
    kernel). Data-dependent output sizes → host-side eager (the reference's
    kernel is CPU/GPU-eager too).

    Returns (reindex_src, reindex_dst, out_nodes): out_nodes is x followed
    by first-appearance neighbor nodes; src/dst are edges in local ids."""
    import numpy as np

    from .tensor_class import wrap
    import jax.numpy as jnp

    xs = _np_of(x).reshape(-1)
    nb = _np_of(neighbors).reshape(-1)
    cnt = _np_of(count).reshape(-1)
    mapping = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    src = np.empty(nb.size, np.int64)
    for i, v in enumerate(nb):
        vi = int(v)
        if vi not in mapping:
            mapping[vi] = len(out_nodes)
            out_nodes.append(vi)
        src[i] = mapping[vi]
    dst = np.repeat(np.arange(xs.size, dtype=np.int64), cnt)
    return (wrap(jnp.asarray(src)), wrap(jnp.asarray(dst)),
            wrap(jnp.asarray(np.asarray(out_nodes, np.int64))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """paddle.geometric.reindex_heter_graph: reindex against several
    neighbor sets sharing one node mapping."""
    import numpy as np

    from .tensor_class import wrap
    import jax.numpy as jnp

    xs = _np_of(x).reshape(-1)
    mapping = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    srcs, dsts = [], []
    for nbr, cnt in zip(neighbors, count):
        nb = _np_of(nbr).reshape(-1)
        c = _np_of(cnt).reshape(-1)
        src = np.empty(nb.size, np.int64)
        for i, v in enumerate(nb):
            vi = int(v)
            if vi not in mapping:
                mapping[vi] = len(out_nodes)
                out_nodes.append(vi)
            src[i] = mapping[vi]
        srcs.append(src)
        dsts.append(np.repeat(np.arange(xs.size, dtype=np.int64), c))
    return (wrap(jnp.asarray(np.concatenate(srcs))),
            wrap(jnp.asarray(np.concatenate(dsts))),
            wrap(jnp.asarray(np.asarray(out_nodes, np.int64))))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """paddle.geometric.sample_neighbors (graph_sample_neighbors kernel):
    uniform sampling from a CSC graph. Host-side eager (data-dependent)."""
    import numpy as np

    from .framework import random as _random
    from .tensor_class import wrap
    import jax.numpy as jnp

    r = _np_of(row).reshape(-1)
    cp = _np_of(colptr).reshape(-1)
    nodes = _np_of(input_nodes).reshape(-1)
    ev = _np_of(eids).reshape(-1) if eids is not None else None
    rng = _host_rng()
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        idx = np.arange(lo, hi)
        if 0 <= sample_size < idx.size:
            idx = rng.choice(idx, size=sample_size, replace=False)
        out_n.append(r[idx])
        out_c.append(idx.size)
        if ev is not None:
            out_e.append(ev[idx])
    neighbors = wrap(jnp.asarray(np.concatenate(out_n) if out_n
                                 else np.empty(0, np.int64)))
    counts = wrap(jnp.asarray(np.asarray(out_c, np.int64)))
    if return_eids:
        if ev is None:
            raise ValueError("return_eids=True requires eids")
        return neighbors, counts, wrap(jnp.asarray(np.concatenate(out_e)))
    return neighbors, counts


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """paddle.geometric.weighted_sample_neighbors: weight-proportional
    sampling without replacement (A-ExpJ reservoir in the reference kernel;
    numpy weighted choice here — same distribution)."""
    import numpy as np

    from .tensor_class import wrap
    import jax.numpy as jnp

    r = _np_of(row).reshape(-1)
    cp = _np_of(colptr).reshape(-1)
    w = _np_of(edge_weight).reshape(-1).astype(np.float64)
    nodes = _np_of(input_nodes).reshape(-1)
    ev = _np_of(eids).reshape(-1) if eids is not None else None
    rng = _host_rng()
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        idx = np.arange(lo, hi)
        if 0 <= sample_size < idx.size:
            p = w[idx] / w[idx].sum()
            idx = rng.choice(idx, size=sample_size, replace=False, p=p)
        out_n.append(r[idx])
        out_c.append(idx.size)
        if ev is not None:
            out_e.append(ev[idx])
    neighbors = wrap(jnp.asarray(np.concatenate(out_n) if out_n
                                 else np.empty(0, np.int64)))
    counts = wrap(jnp.asarray(np.asarray(out_c, np.int64)))
    if return_eids:
        if ev is None:
            raise ValueError("return_eids=True requires eids")
        return neighbors, counts, wrap(jnp.asarray(np.concatenate(out_e)))
    return neighbors, counts
