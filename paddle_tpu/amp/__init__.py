"""Automatic mixed precision.

Reference parity: python/paddle/amp/ — ``auto_cast`` (O1/O2 with per-op
white/black lists, auto_cast.py:462,1029), ``decorate`` (:1114), ``GradScaler``
(grad_scaler.py:657).

TPU-native design: the native compute dtype is bfloat16, whose exponent range
matches f32 — so **loss scaling is unnecessary** (GradScaler is kept for API
parity and behaves as configured but defaults to enable=True/no-op scaling
under bf16). The autocast decision is made at op-dispatch time: the eager op
registry consults the active AmpState (the role eager_gen.py:596 plays in
every generated fwd function of the reference).
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..framework import dtype as _dtype_mod
from ..tensor_class import Tensor, unwrap, wrap

# Per-op lists, mirroring the reference's default white/black lists
# (python/paddle/amp/amp_lists.py): white → run in low precision,
# black → force f32.
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv", "conv_transpose", "einsum",
    "flash_attention", "sdpa", "addmm",
}
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos_sim",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "layer_norm", "rms_norm", "batch_norm", "group_norm", "instance_norm",
    "norm", "logsumexp", "cumsum", "pow", "erf", "erfinv",
}

_state = threading.local()


class AmpState:
    __slots__ = ("enabled", "dtype", "level", "custom_white", "custom_black")

    def __init__(self, enabled, dtype, level, custom_white=None, custom_black=None):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.custom_white = set(custom_white or ())
        self.custom_black = set(custom_black or ())


def _amp_state() -> AmpState | None:
    return getattr(_state, "amp", None)


def amp_dtype_for(op_name: str):
    """Called by the op registry: returns the compute dtype this op should
    cast float inputs to, or None for no cast."""
    st = _amp_state()
    if st is None or not st.enabled:
        return None
    name = op_name.lower()
    if name in st.custom_black or name in BLACK_LIST:
        return jnp.float32
    if st.level == "O2":
        return st.dtype
    if name in st.custom_white or name in WHITE_LIST:
        return st.dtype
    return None


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast parity (auto_cast.py:1029)."""
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    prev = _amp_state()
    _state.amp = AmpState(enable and level != "O0", _dtype_mod.convert_dtype(dtype),
                          level, custom_white_list, custom_black_list)
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False, excluded_layers=None):
    """paddle.amp.decorate parity (auto_cast.py:1114): O2 casts model params to
    the low-precision dtype (master f32 copies live in the optimizer state)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        from ..nn.layers_common import _BatchNormBase
        from ..nn.layers_common import LayerNorm

        excluded = tuple(excluded_layers) if excluded_layers else (_BatchNormBase, LayerNorm)
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, excluded):
                    continue
                for p in layer._parameters.values():
                    if p is not None and _dtype_mod.is_floating_point_dtype(p.dtype):
                        p._array = p._array.astype(_dtype_mod.convert_dtype(dtype))
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


class GradScaler:
    """paddle.amp.GradScaler parity (grad_scaler.py:657). On TPU/bf16 loss
    scaling is a no-op numerically, but dynamic-scale bookkeeping is kept so
    fp16 workflows and checkpoints behave identically."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._already_unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return wrap(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._already_unscaled:
            return
        self._already_unscaled = True
        found = False
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                g = unwrap(p.grad) / self._scale
                p.grad._array = g
                if not bool(jnp.isfinite(g).all()):
                    found = True
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        optimizer.clear_grad()

    def update(self):
        self._already_unscaled = False
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
        }

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("incr_count", 0)
        self._bad_steps = sd.get("decr_count", 0)


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


class debugging:
    """Namespace stub mirroring paddle.amp.debugging (nan/inf checks live in
    utils/debugging.py)."""

    @staticmethod
    def enable_operator_stats_collection():
        pass

    @staticmethod
    def disable_operator_stats_collection():
        pass
