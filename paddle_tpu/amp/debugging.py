"""paddle.amp.debugging parity (python/paddle/amp/debugging.py): operator
stats collection, tensor checking (NaN/Inf), accuracy comparison.

TPU-native: the op registry's single dispatch choke point
(ops/registry.py::apply) is the hook — stats count every eager op by
dtype; the tensor checker rides FLAGS_check_nan_inf (which also covers the
compiled TrainStep path via checkify).
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass, field

__all__ = ["enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "compare_accuracy",
           "check_numerics", "DebugMode"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


_STATS = None


def _record(op_name: str, dtypes) -> None:
    if _STATS is None:
        return
    for dt in dtypes:
        key = str(dt)
        bucket = _STATS.setdefault(op_name, {})
        bucket[key] = bucket.get(key, 0) + 1


def enable_operator_stats_collection() -> None:
    """Start counting dispatched ops per dtype (op_stats_ hook parity)."""
    global _STATS
    _STATS = {}
    from ..ops import registry

    registry.set_stats_hook(_record)


def disable_operator_stats_collection() -> None:
    """Stop collecting and print the table like the reference."""
    global _STATS
    from ..ops import registry

    registry.set_stats_hook(None)
    stats, _STATS = _STATS, None
    if stats is None:
        return
    print("<{:-^120}>".format(" op list "))
    print("{:<40}|{:<40}|{:<20}".format("op", "dtype", "calls"))
    for op, by_dtype in sorted(stats.items()):
        for dt, n in sorted(by_dtype.items()):
            print("{:<40}|{:<40}|{:<20}".format(op, dt, n))
    print("<{:-^120}>".format(""))


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def operator_stats_snapshot():
    """Current counts (test/introspection hook; not in the reference API)."""
    return {} if _STATS is None else {k: dict(v) for k, v in _STATS.items()}


@dataclass
class TensorCheckerConfig:
    """paddle.amp.debugging.TensorCheckerConfig parity."""

    enable: bool = True
    debug_mode: int = DebugMode.CHECK_NAN_INF_AND_ABORT
    output_dir: str | None = None
    checked_op_list: list = field(default_factory=list)
    skipped_op_list: list = field(default_factory=list)
    debug_step: tuple | None = None
    stack_height_limit: int = 1


_CHECKER_PREV = None


def enable_tensor_checker(checker_config: TensorCheckerConfig) -> None:
    """Route through FLAGS_check_nan_inf — the registry raises on the first
    non-finite op output (and TrainStep compiles under checkify)."""
    global _CHECKER_PREV
    from ..utils import flags

    if _CHECKER_PREV is None:  # idempotent: keep the ORIGINAL state
        _CHECKER_PREV = flags.get_flags("FLAGS_check_nan_inf")
    flags.set_flags({"FLAGS_check_nan_inf": bool(checker_config.enable)})


def disable_tensor_checker() -> None:
    global _CHECKER_PREV
    from ..utils import flags

    prev = _CHECKER_PREV if _CHECKER_PREV is not None else {}
    flags.set_flags({"FLAGS_check_nan_inf":
                     prev.get("FLAGS_check_nan_inf", False)
                     if isinstance(prev, dict) else False})
    _CHECKER_PREV = None


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """paddle.amp.debugging.check_numerics: raise on NaN/Inf now."""
    import jax.numpy as jnp

    from ..tensor_class import unwrap

    a = unwrap(tensor)
    if jnp.issubdtype(a.dtype, jnp.floating) and not bool(
            jnp.isfinite(a).all()):
        raise FloatingPointError(
            f"check_numerics: non-finite values in {op_type or 'tensor'} "
            f"{var_name}")
    return tensor


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """paddle.amp.debugging.compare_accuracy: diff two tensor dumps (as
    produced by incubate.accuracy_check / numpy .npz dumps) into a CSV."""
    import csv
    import os

    import numpy as np

    def load(p):
        out = {}
        for f in sorted(os.listdir(p)):
            if f.endswith((".npy", ".npz")):
                arr = np.load(os.path.join(p, f), allow_pickle=False)
                out[f] = arr[arr.files[0]] if hasattr(arr, "files") else arr
        return out

    a, b = load(dump_path), load(another_dump_path)
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["tensor", "max_abs_diff", "mean_abs_diff", "shape_match"])
        for name in sorted(set(a) | set(b)):
            if name in a and name in b and a[name].shape == b[name].shape:
                d = np.abs(a[name].astype(np.float64)
                           - b[name].astype(np.float64))
                w.writerow([name, d.max(), d.mean(), True])
            else:
                w.writerow([name, "", "", False])
    return output_filename
