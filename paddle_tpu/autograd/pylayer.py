"""PyLayer — user-defined autograd ops.

Reference parity: paddle.autograd.PyLayer
(paddle/fluid/eager/pylayer/, paddle/fluid/pybind/eager_py_layer.cc).
TPU-native: the user's forward/backward pair becomes a custom tape node; the
generic backward walk (autograd/tape.py) dispatches to ``run_backward``.
"""
from __future__ import annotations

import weakref

import jax.numpy as jnp

from ..tensor_class import Tensor, unwrap, wrap
from . import tape as _tape


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True
        self._non_differentiable = set()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_non_differentiable(self, *tensors):
        self._non_differentiable.update(id(t) for t in tensors)

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


class _PyLayerNode:
    """Tape node whose backward calls the user's backward() instead of jax.vjp."""

    __slots__ = ("cls", "ctx", "in_tensors", "out_refs", "name", "__weakref__")

    def __init__(self, cls, ctx, in_tensors, outputs):
        self.cls = cls
        self.ctx = ctx
        self.in_tensors = tuple(in_tensors)
        self.out_refs = tuple(weakref.ref(o) for o in outputs)
        self.name = cls.__name__

    def run_backward(self, outs, gs):
        grads_in = []
        for o, g in zip(outs, gs):
            if g is None and self.ctx.materialize_grads and o is not None:
                g = jnp.zeros_like(o._array)
            grads_in.append(wrap(g) if g is not None else None)
        result = self.cls.backward(self.ctx, *grads_in)
        if not isinstance(result, (tuple, list)):
            result = (result,)
        return [unwrap(r) if isinstance(r, Tensor) else r for r in result]


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError("PyLayer subclasses are used via .apply(), not instantiated")


class PyLayer(metaclass=PyLayerMeta):
    """Subclass with @staticmethod forward(ctx, *args) / backward(ctx, *grads)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):  # pragma: no cover - abstract
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        outputs = cls.forward(ctx, *args, **kwargs)
        outs = [outputs] if not isinstance(outputs, (tuple, list)) else list(outputs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        requires_grad = _tape.grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        out_tensors = [o for o in outs if isinstance(o, Tensor)]
        if requires_grad and out_tensors:
            node = _PyLayerNode(cls, ctx, tensor_inputs, out_tensors)
            _tape._st().tape.append(node)
            for o in out_tensors:
                if id(o) not in ctx._non_differentiable:
                    o.stop_gradient = False
                    o._grad_node = node
        return outputs
