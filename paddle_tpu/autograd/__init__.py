from .tape import (
    backward,
    grad,
    no_grad,
    enable_grad,
    set_grad_enabled,
    grad_enabled,
    reset_tape,
)

# PyLayer imported lazily: pylayer.py needs tensor_class, which imports this
# package for the tape (tensor → tape → [lazy] pylayer → tensor).


def __getattr__(name):
    if name in ("PyLayer", "PyLayerContext"):
        from . import pylayer

        globals()["PyLayer"] = pylayer.PyLayer
        globals()["PyLayerContext"] = pylayer.PyLayerContext
        return globals()[name]
    if name in ("jacobian", "hessian", "saved_tensors_hooks"):
        from . import functional as _f

        globals()[name] = getattr(_f, name)
        return globals()[name]
    raise AttributeError(f"module 'paddle_tpu.autograd' has no attribute {name!r}")


def __dir__():
    # lazy names must be introspectable, not just gettable
    return sorted(set(globals()) | {"PyLayer", "PyLayerContext",
                                    "jacobian", "hessian",
                                    "saved_tensors_hooks"})
