"""Eager autograd engine: a gradient tape over functional JAX.

Reference parity: the reference's eager autograd records a ``GradNode`` per op
with saved inputs and runs a topologically-ordered backward queue walk
(`paddle/fluid/eager/backward.cc:105,439`, `paddle/fluid/eager/grad_node_info.h`).

TPU-native design: instead of per-op handwritten grad kernels, every recorded
node stores the *pure jax function* and its input arrays; backward calls
``jax.vjp`` on that function. Execution order on the tape is a valid
topological order of the autograd DAG, so the backward pass is simply a
reverse walk with cotangent accumulation — no in-degree bookkeeping needed.
The performance-critical path does not use this engine at all: training steps
are traced to a single XLA computation via ``jax.grad`` (see paddle_tpu.jit).
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

_state = threading.local()


def _st():
    if not hasattr(_state, "tape"):
        _state.tape = []
        _state.enabled = True
        _state.depth = 0
    return _state


def grad_enabled() -> bool:
    return _st().enabled


def set_grad_enabled(mode: bool) -> bool:
    st = _st()
    prev = st.enabled
    st.enabled = bool(mode)
    return prev


class no_grad:
    """paddle.no_grad parity — context manager and decorator."""

    def __enter__(self):
        self._prev = set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class GradNode:
    """One recorded op: pure fn + input arrays + the participating tensors.

    ``fn`` maps the *differentiable* input arrays to the op's output array(s)
    (non-tensor and non-differentiable args are closed over).
    """

    __slots__ = ("fn", "in_arrays", "in_tensors", "out_refs", "name",
                 "unpack_hook", "__weakref__")

    def __init__(self, fn, in_arrays, in_tensors, outputs, name=""):
        self.fn = fn
        self.in_arrays = tuple(in_arrays)
        self.in_tensors = tuple(in_tensors)  # strong refs: grads accumulate here
        self.out_refs = tuple(weakref.ref(o) for o in outputs)
        self.name = name
        self.unpack_hook = None  # saved_tensors_hooks: set iff pack ran


_SAVED_PACK = None
_SAVED_UNPACK = None
_IN_PACK = False


def set_saved_tensors_hooks(pack_hook, unpack_hook):
    """autograd.saved_tensors_hooks plumbing: pack transforms each saved
    input array at record time; the matching unpack callable is CAPTURED ON
    THE NODE, so backward after the context exits (the standard offload
    pattern) still restores packed residuals, and nodes recorded outside
    the context are never spuriously unpacked."""
    global _SAVED_PACK, _SAVED_UNPACK
    _SAVED_PACK = pack_hook
    _SAVED_UNPACK = unpack_hook


def record(fn: Callable, in_arrays: Sequence[Any], in_tensors: Sequence[Any], outputs: Sequence[Any], name: str = ""):
    """Append a node to the active tape and link outputs to it."""
    global _IN_PACK

    unpack = None
    if _SAVED_PACK is not None and not _IN_PACK:
        from ..tensor_class import unwrap as _unw, wrap as _wrp

        # re-entrancy guard: a pack hook that dispatches registry ops
        # (e.g. t.cast) records nodes of its own — those must not re-pack
        _IN_PACK = True
        try:
            in_arrays = [
                _unw(_SAVED_PACK(_wrp(a))) if isinstance(a, jax.Array) else a
                for a in in_arrays]
        finally:
            _IN_PACK = False
        unpack = _SAVED_UNPACK
    node = GradNode(fn, in_arrays, in_tensors, outputs, name)
    node.unpack_hook = unpack
    _st().tape.append(node)
    for o in outputs:
        o._grad_node = node
    return node


def reset_tape():
    _st().tape = []


def _ones_like(arr):
    return jnp.ones_like(arr)


def _zero_cotangent(p):
    import numpy as np

    if jnp.issubdtype(p.dtype, jnp.inexact):
        return jnp.zeros_like(p)
    return np.zeros(p.shape, dtype=jax.dtypes.float0)


def _match_cotangent(g, p):
    """Cast/derive a cotangent matching primal ``p``'s JAX type."""
    if g is None:
        return _zero_cotangent(p)
    if jnp.issubdtype(p.dtype, jnp.inexact) and g.dtype != p.dtype:
        return g.astype(p.dtype)
    return g


def backward(tensors, grad_tensors=None, retain_graph: bool = False, grads_out=None):
    """Run reverse-mode accumulation from ``tensors`` over the recorded tape.

    Parity: ``egr::Backward`` (paddle/fluid/eager/backward.cc:439). Leaf
    tensors (those with stop_gradient=False and no grad node) receive ``.grad``
    (the role of GradNodeAccumulation, paddle/fluid/eager/accumulation/).

    When ``grads_out`` (a dict ``id(tensor) -> accumulated grad array``) is
    given, the walk runs in "Grad mode" (backward.cc:450): nothing touches
    ``.grad``; contributions for the requested tensor ids (leaf or not) are
    collected into the dict instead.
    """
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangent accumulator keyed by tensor identity
    cotan: dict[int, Any] = {}
    keep_alive: dict[int, Any] = {}

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError("backward() called on a tensor with stop_gradient=True")
        seed = g._array if hasattr(g, "_array") else g
        if seed is None:
            if t._array.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {tuple(t._array.shape)}"
                )
            seed = _ones_like(t._array)
        cotan[id(t)] = seed
        keep_alive[id(t)] = t

    tape = _st().tape
    consumed = set()
    for node in reversed(tape):
        outs = [r() for r in node.out_refs]
        gs = [cotan.pop(id(o), None) if o is not None else None for o in outs]
        for o in outs:
            keep_alive.pop(id(o), None)
        if all(g is None for g in gs):
            continue
        consumed.add(id(node))
        if hasattr(node, "run_backward"):
            # custom node (PyLayer): user-supplied backward
            in_grads = node.run_backward(outs, gs)
        else:
            # fill missing output cotangents with zeros (float0 for int
            # outputs) and match the primal dtype — under AMP a node's
            # consumer may run in a different precision than the node itself
            saved = node.in_arrays
            if node.unpack_hook is not None:
                from ..tensor_class import unwrap as _unw, wrap as _wrp

                global _IN_PACK
                _IN_PACK = True  # unpack hooks may dispatch ops too
                try:
                    saved = tuple(
                        _unw(node.unpack_hook(_wrp(a)))
                        if isinstance(a, jax.Array) else a for a in saved)
                finally:
                    _IN_PACK = False
            primals_out, vjp_fn = jax.vjp(node.fn, *saved)
            if isinstance(primals_out, (tuple, list)):
                filled = tuple(
                    _match_cotangent(g, p) for g, p in zip(gs, primals_out)
                )
                in_grads = vjp_fn(filled)
            else:
                in_grads = vjp_fn(_match_cotangent(gs[0], primals_out))
        from ..ops.registry import _check_nan_inf

        _check_nan_inf(f"{node.name}_grad", list(in_grads))
        for t, g in zip(node.in_tensors, in_grads):
            if t is None or g is None or t.stop_gradient:
                continue
            if getattr(g, "dtype", None) == jax.dtypes.float0:
                continue
            tid = id(t)
            if grads_out is not None:
                if tid in grads_out:
                    prev = grads_out[tid]
                    grads_out[tid] = g if prev is None else prev + g
            elif t.is_leaf:
                t._accumulate_grad(g)
            if not t.is_leaf:
                # non-leaf: pass the contribution upstream (for an in-place
                # op, t is its own output — the deposit reaches t's original
                # producer node, whose out_refs still point at t)
                cotan[tid] = cotan[tid] + g if tid in cotan else g
                keep_alive[tid] = t
        # fire user hooks registered on output tensors
        for o, g in zip(outs, gs):
            if o is not None and g is not None and o._backward_hooks:
                for hook in o._backward_hooks:
                    hook(g)

    if not retain_graph:
        # free the walked graph; also GC nodes whose every output tensor has
        # died — they can never receive a cotangent again, and keeping them
        # leaks their saved arrays (the create_graph training-loop pattern
        # retains forward nodes that no later backward ever consumes)
        st = _st()
        st.tape = [
            n for n in st.tape
            if id(n) not in consumed and any(r() is not None for r in n.out_refs)
        ]


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused,
                       retain_graph=None):
    """``paddle.grad(create_graph=True)``: differentiable gradients.

    TPU-native higher-order AD (role of the reference's prim/vjp_interface,
    paddle/fluid/primitive/): slice the recorded tape to the subgraph between
    ``inputs`` and ``outputs``, rebuild it as one pure composite function, and
    take ``jax.vjp`` of the composite. The whole grad computation is recorded
    back onto the tape as a single node, so a further backward()/grad() call
    differentiates *through* it via jax's composable transforms — no manual
    double-backward rules needed.
    """
    from ..tensor_class import Tensor

    # a duplicated input would collapse in the id-keyed replay env and the
    # later occurrences would shadow the earlier positional bindings in
    # jax.vjp — dedupe here and fan the per-unique grads back out (paddle
    # gives every duplicate the full gradient)
    uniq, pos_of = [], []
    seen: dict[int, int] = {}
    for t in inputs:
        if id(t) not in seen:
            seen[id(t)] = len(uniq)
            uniq.append(t)
        pos_of.append(seen[id(t)])
    if len(uniq) != len(inputs):
        res_u = _grad_create_graph(outputs, uniq, grad_outputs, allow_unused,
                                   retain_graph)
        return [res_u[i] for i in pos_of]

    tape = list(_st().tape)
    input_ids = {id(t) for t in inputs}

    # forward slice: nodes whose output depends (transitively) on any input
    reach = set(input_ids)
    fwd_nodes = []
    for node in tape:
        depends = any(
            t is not None and id(t) in reach for t in node.in_tensors
        )
        if not depends:
            continue
        fwd_nodes.append(node)
        for r in node.out_refs:
            o = r()
            if o is not None:
                reach.add(id(o))

    # backward slice: keep only nodes some requested output depends on
    needed = {id(t) for t in outputs}
    used = []
    for node in reversed(fwd_nodes):
        if any(r() is not None and id(r()) in needed for r in node.out_refs):
            used.append(node)
            for t in node.in_tensors:
                if t is not None:
                    needed.add(id(t))
    used.reverse()
    # only the pruned slice matters: a PyLayer elsewhere on the tape is fine
    for node in used:
        if hasattr(node, "run_backward"):
            raise RuntimeError(
                "paddle.grad(create_graph=True) through a PyLayer is not "
                "supported; implement the op with a jax-differentiable "
                "function (or jax.custom_vjp) instead"
            )

    used_input_ids = input_ids & needed
    if not allow_unused:
        for t in inputs:
            if id(t) not in used_input_ids:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not have "
                    "been used in the graph. Set allow_unused=True if this "
                    "is desired."
                )

    n_in = len(inputs)

    def _composite(in_arrays):
        env = {id(t): a for t, a in zip(inputs, in_arrays)}
        for node in used:
            args = [
                env.get(id(t), a) if t is not None else a
                for t, a in zip(node.in_tensors, node.in_arrays)
            ]
            res = node.fn(*args)
            res = res if isinstance(res, (tuple, list)) else (res,)
            for r, a in zip(node.out_refs, res):
                o = r()
                if o is not None:
                    env[id(o)] = a
        # an output independent of inputs contributes a constant (zero grad)
        return tuple(
            env.get(id(t), t._array) for t in outputs
        )

    # seed cotangents
    seeds = []
    seed_tensors = []
    gos = grad_outputs or [None] * len(outputs)
    for t, g in zip(outputs, gos):
        if g is None:
            if t._array.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {tuple(t._array.shape)}"
                )
            seeds.append(jnp.ones_like(t._array))
            seed_tensors.append(None)
        else:
            seeds.append(g._array if hasattr(g, "_array") else jnp.asarray(g))
            seed_tensors.append(g if hasattr(g, "_array") else None)

    def _grad_fn(*arrs):
        prim, cots = arrs[:n_in], arrs[n_in:]
        _, vjp_fn = jax.vjp(lambda *xs: _composite(xs), *prim)
        return vjp_fn(tuple(cots))

    in_arrays = [t._array for t in inputs] + list(seeds)
    grads = _grad_fn(*in_arrays)

    results = []
    out_tensors = []
    for t, g in zip(inputs, grads):
        if id(t) not in used_input_ids:
            results.append(None)
            continue
        r = Tensor._wrap(g, stop_gradient=False)
        results.append(r)
        out_tensors.append(r)
    if out_tensors:
        record(
            _grad_fn,
            in_arrays,
            list(inputs) + seed_tensors,
            out_tensors,
            name="grad",
        )
        # record() links each output to the node positionally by out_refs;
        # the node returns one grad per input, so outputs must line up with
        # the full grads tuple — rebuild out_refs including unused slots.
        node = out_tensors[0]._grad_node
        node.out_refs = tuple(
            weakref.ref(r) if r is not None else _dead_ref for r in results
        )
    if retain_graph is False:
        # paddle semantics: retain_graph defaults to create_graph, but an
        # explicit False frees the differentiated forward slice (the recorded
        # grad node stays usable — it closes over the composite's arrays)
        dropped = {id(n) for n in used}
        st = _st()
        st.tape = [n for n in st.tape if id(n) not in dropped]
    return results


def _dead_ref():
    return None


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    allow_unused=False,
):
    """paddle.grad parity (paddle/fluid/eager/backward.cc:450 ``Grad``):
    compute grads of outputs w.r.t. inputs without touching ``.grad``.

    With ``create_graph=True`` the returned grads are themselves
    differentiable (recorded on the tape via a jax.vjp composite — see
    ``_grad_create_graph``)."""
    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs, allow_unused,
                                  retain_graph)

    collected = {id(t): None for t in inputs}
    retain = True if retain_graph is None else retain_graph
    backward(list(outputs), grad_outputs, retain_graph=retain, grads_out=collected)
    results = []
    for t in inputs:
        g = collected[id(t)]
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been used "
                "in the graph. Set allow_unused=True if this is desired."
            )
        from ..tensor_class import Tensor

        results.append(Tensor._wrap(g) if g is not None else None)
    if retain_graph is None:
        reset_tape()
    return results
