"""paddle.autograd functional APIs: jacobian / hessian (python/paddle/
autograd/autograd.py) and saved_tensors_hooks (saved_tensors_hooks.py).

TPU-native: jacobian/hessian lower straight onto jax.jacrev/jax.hessian —
the composable-transform path the reference builds by stacking vjp calls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor_class import Tensor, unwrap, wrap


class _LazyMatrix:
    """Matrix façade over a computed jacobian/hessian block (the reference
    returns lazily-evaluated Jacobian/Hessian objects; slicing works the
    same — here the block is materialized by jax on construction)."""

    def __init__(self, arr):
        self._arr = arr

    def __getitem__(self, idx):
        return wrap(self._arr[idx])

    @property
    def shape(self):
        return list(self._arr.shape)

    def numpy(self):
        import numpy as np

        return np.asarray(self._arr)

    def __repr__(self):
        return f"Jacobian(shape={self.shape})"


def _call_flat(func, xs):
    def fn(*arrs):
        ten = [wrap(a, stop_gradient=False) for a in arrs]
        out = func(*ten) if len(ten) > 1 else func(ten[0])
        return unwrap(out if not isinstance(out, (list, tuple)) else out[0])

    return fn


def jacobian(ys, xs, batch_axis=None):
    """paddle.autograd.jacobian: d(ys)/d(xs).

    Two call forms, both supported:
    - jacobian(func, xs): func evaluated at xs (tensor or list);
    - jacobian(ys, xs) with ys already computed on the tape: falls back to
      re-deriving via paddle.grad rows.
    """
    if callable(ys):
        func = ys
        inputs = xs if isinstance(xs, (list, tuple)) else [xs]
        arrs = [unwrap(x) for x in inputs]
        jac = jax.jacrev(_call_flat(func, inputs),
                         argnums=tuple(range(len(arrs))))(*arrs)
        if len(arrs) == 1:
            return _LazyMatrix(jac[0])
        return [_LazyMatrix(j) for j in jac]
    # tape form: build rows with paddle.grad (one vjp per output element);
    # unused inputs yield zero blocks, every requested output contributes
    from .tape import grad as _grad

    ys_t = ys if isinstance(ys, (list, tuple)) else [ys]
    xs_t = xs if isinstance(xs, (list, tuple)) else [xs]
    per_y = []
    for y in ys_t:
        flat_n = int(unwrap(y).size)
        rows = []
        for i in range(flat_n):
            seed = jnp.zeros((flat_n,), unwrap(y).dtype).at[i].set(1.0)
            gs = _grad([y], xs_t, grad_outputs=[wrap(
                seed.reshape(unwrap(y).shape))], retain_graph=True,
                allow_unused=True)
            rows.append([
                unwrap(g).reshape(-1) if g is not None
                else jnp.zeros((int(unwrap(x).size),), unwrap(y).dtype)
                for g, x in zip(gs, xs_t)])
        mats = []
        for k, x in enumerate(xs_t):
            mat = jnp.stack([r[k] for r in rows])
            mats.append(_LazyMatrix(mat.reshape(
                tuple(unwrap(y).shape) + tuple(unwrap(x).shape))))
        per_y.append(mats[0] if not isinstance(xs, (list, tuple)) else mats)
    if not isinstance(ys, (list, tuple)):
        return per_y[0]
    return per_y


def hessian(func, xs, batch_axis=None):
    """paddle.autograd.hessian: d²(func)/d(xs)² for scalar-output func."""
    if not callable(func):
        raise TypeError("hessian expects a callable returning a scalar")
    inputs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrs = [unwrap(x) for x in inputs]
    hes = jax.hessian(_call_flat(func, inputs),
                      argnums=tuple(range(len(arrs))))(*arrs)
    if len(arrs) == 1:
        return _LazyMatrix(hes[0][0])
    return [[_LazyMatrix(b) for b in row] for row in hes]


class saved_tensors_hooks:
    """paddle.autograd.saved_tensors_hooks: transform tensors stashed for
    backward (pack on save, unpack on use) — the activation-offload /
    compression hook. Plugged into the tape's residual save/load path."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from . import tape

        tape.set_saved_tensors_hooks(self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        from . import tape

        tape.set_saved_tensors_hooks(None, None)
        return False
