"""paddle_tpu.io — datasets, samplers, DataLoader.

Reference parity: python/paddle/io/ (Dataset/IterableDataset/TensorDataset/
Subset/random_split/ConcatDataset/ChainDataset, BatchSampler,
DistributedBatchSampler at dataloader/batch_sampler.py:192, multiprocess
DataLoader at dataloader/dataloader_iter.py + worker.py).

TPU-native notes: the hot path feeds jnp arrays; multiprocess workers use the
standard multiprocessing pool producing numpy batches (host-side), and
device transfer happens at iteration time (async via jax device_put). The
reference's shared-memory tensor transport is unnecessary — numpy pickling
through the pool plays the same role on a single host.
"""
from .dataset import (
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler, SubsetRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn, get_worker_info
