"""Shared-memory batch channel for DataLoader workers.

Reference parity: the reference DataLoader's use_shared_memory transport
(python/paddle/io/dataloader/worker.py `_worker_loop` + core memory-mapped
tensor channel): worker processes hand finished batches to the main
process through shared memory instead of pickling into a pipe. Here the
ring itself is C++ (core/csrc/shm_queue.cpp); numpy batches serialize as
a tiny header + raw array bytes (zero pickle on the payload).
"""
from __future__ import annotations

import ctypes
import io
import os
import pickle
import struct
from typing import Any

import numpy as np

from ..core import load_native


def _pack(obj: Any) -> bytes:
    """Fast path: (nested) numpy arrays go as raw bytes; the structure is a
    small pickled skeleton with placeholders."""
    arrays = []

    def strip(o):
        if isinstance(o, np.ndarray):
            arrays.append(o)
            return ("__nd__", len(arrays) - 1, o.shape, str(o.dtype))
        if isinstance(o, (list, tuple)):
            t = [strip(x) for x in o]
            return tuple(t) if isinstance(o, tuple) else t
        if isinstance(o, dict):
            return {k: strip(v) for k, v in o.items()}
        return o

    skeleton = pickle.dumps(strip(obj), protocol=4)
    parts = [struct.pack("<II", len(skeleton), len(arrays)), skeleton]
    for a in arrays:
        b = np.ascontiguousarray(a).tobytes()
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    return b"".join(parts)


def _unpack(buf: bytes) -> Any:
    sk_len, n_arr = struct.unpack_from("<II", buf, 0)
    off = 8
    skeleton = pickle.loads(buf[off:off + sk_len])
    off += sk_len
    arrays = []
    for _ in range(n_arr):
        (blen,) = struct.unpack_from("<Q", buf, off)
        off += 8
        arrays.append((off, blen))
        off += blen

    def rebuild(o):
        if isinstance(o, tuple) and len(o) == 4 and o[0] == "__nd__":
            _, i, shape, dtype = o
            aoff, alen = arrays[i]
            return np.frombuffer(buf, np.dtype(dtype), count=alen // np.dtype(dtype).itemsize,
                                 offset=aoff).reshape(shape).copy()
        if isinstance(o, tuple):
            return tuple(rebuild(x) for x in o)
        if isinstance(o, list):
            return [rebuild(x) for x in o]
        if isinstance(o, dict):
            return {k: rebuild(v) for k, v in o.items()}
        return o

    return rebuild(skeleton)


class ShmChannelTimeout(TimeoutError):
    """A put()/get() deadline elapsed. Subclasses TimeoutError so existing
    ``except TimeoutError`` consumers (the DataLoader drain loop) keep
    working, but carries what a bare TimeoutError couldn't: WHICH channel
    stalled and how full its ring was at the moment of the timeout — the
    two facts that distinguish a dead producer (empty) from a stuck
    consumer (full) without a debugger."""

    def __init__(self, message: str, *, channel: str, qsize: int,
                 op: str = "?"):
        super().__init__(message)
        self.channel = channel
        self.qsize = qsize
        self.op = op  # "put" | "get"


class ShmChannel:
    """Process-shared bounded queue of python batches over the C++ ring."""

    def __init__(self, name: str = None, capacity_mb: int = 64,
                 create: bool = True):
        # order matters for a failed constructor: __del__ runs on partial
        # objects, so _h must exist before anything here can raise
        self._h = None
        self._lib = load_native()
        self.name = name or f"/pdtpu_q_{os.getpid()}_{id(self) & 0xFFFF}"
        if create:
            self._h = self._lib.pd_shmq_create(self.name.encode(),
                                               capacity_mb * 1024 * 1024)
        else:
            self._h = self._lib.pd_shmq_open(self.name.encode())
        if not self._h:
            self._h = None
            raise RuntimeError(f"shm queue {'create' if create else 'open'} "
                               f"failed for {self.name}")
        self._owner = create

    def open_in_child(self) -> "ShmChannel":
        return ShmChannel(self.name, create=False)

    def _handle(self):
        """The live native handle, or a clear error once closed — a None
        handle passed into ctypes would segfault, not raise."""
        if self._h is None:
            raise BrokenPipeError(f"shm channel {self.name} is closed")
        return self._h

    def put(self, obj: Any, timeout: float = 300.0) -> None:
        h = self._handle()
        data = _pack(obj)
        rc = self._lib.pd_shmq_push(h, data, len(data), timeout)
        if rc == 1:
            depth = self.qsize()
            raise ShmChannelTimeout(
                f"shm channel {self.name}: put timed out after {timeout}s "
                f"(ring full, qsize={depth}) — the consumer is not "
                f"draining", channel=self.name, qsize=depth, op="put")
        if rc == -2:
            raise BrokenPipeError(f"shm channel {self.name} closed")
        if rc != 0:
            raise RuntimeError(f"shm push failed (batch {len(data)} bytes "
                               f"exceeds ring capacity?)")

    def get(self, timeout: float = 300.0) -> Any:
        h = self._handle()
        out = ctypes.POINTER(ctypes.c_char)()
        n = self._lib.pd_shmq_pop(h, ctypes.byref(out), timeout)
        if n == -2:
            raise ShmChannelTimeout(
                f"shm channel {self.name}: get timed out after {timeout}s "
                f"(ring empty, qsize=0) — no producer delivered",
                channel=self.name, qsize=0, op="get")
        if n == -3:
            raise EOFError("shm queue closed and drained")
        if n < 0:
            raise RuntimeError("shm pop failed")
        buf = ctypes.string_at(out, n)
        self._lib.pd_shmq_free(out)
        return _unpack(buf)

    def qsize(self) -> int:
        return int(self._lib.pd_shmq_count(self._handle()))

    def close_writers(self):
        self._lib.pd_shmq_close_writers(self._handle())

    def close(self):
        """Idempotent: the handle is swapped out BEFORE the native close,
        so a second close() (or a close() racing __del__ at teardown) is
        a no-op instead of a double-free on the same native handle."""
        h, self._h = self._h, None
        if h is not None:
            self._lib.pd_shmq_close(h)

    def __del__(self):
        try:
            # getattr: __init__ may have failed before _h existed
            if getattr(self, "_h", None) is not None:
                self.close()
        except Exception:  # pdlint: disable=silent-exception -- interpreter teardown: ctypes/logging may already be gone
            pass
