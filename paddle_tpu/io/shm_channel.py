"""Shared-memory batch channel for DataLoader workers.

Reference parity: the reference DataLoader's use_shared_memory transport
(python/paddle/io/dataloader/worker.py `_worker_loop` + core memory-mapped
tensor channel): worker processes hand finished batches to the main
process through shared memory instead of pickling into a pipe. Here the
ring itself is C++ (core/csrc/shm_queue.cpp); numpy batches serialize as
a tiny header + raw array bytes (zero pickle on the payload).
"""
from __future__ import annotations

import ctypes
import io
import os
import pickle
import struct
from typing import Any

import numpy as np

from ..core import load_native


def _pack(obj: Any) -> bytes:
    """Fast path: (nested) numpy arrays go as raw bytes; the structure is a
    small pickled skeleton with placeholders."""
    arrays = []

    def strip(o):
        if isinstance(o, np.ndarray):
            arrays.append(o)
            return ("__nd__", len(arrays) - 1, o.shape, str(o.dtype))
        if isinstance(o, (list, tuple)):
            t = [strip(x) for x in o]
            return tuple(t) if isinstance(o, tuple) else t
        if isinstance(o, dict):
            return {k: strip(v) for k, v in o.items()}
        return o

    skeleton = pickle.dumps(strip(obj), protocol=4)
    parts = [struct.pack("<II", len(skeleton), len(arrays)), skeleton]
    for a in arrays:
        b = np.ascontiguousarray(a).tobytes()
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    return b"".join(parts)


def _unpack(buf: bytes) -> Any:
    sk_len, n_arr = struct.unpack_from("<II", buf, 0)
    off = 8
    skeleton = pickle.loads(buf[off:off + sk_len])
    off += sk_len
    arrays = []
    for _ in range(n_arr):
        (blen,) = struct.unpack_from("<Q", buf, off)
        off += 8
        arrays.append((off, blen))
        off += blen

    def rebuild(o):
        if isinstance(o, tuple) and len(o) == 4 and o[0] == "__nd__":
            _, i, shape, dtype = o
            aoff, alen = arrays[i]
            return np.frombuffer(buf, np.dtype(dtype), count=alen // np.dtype(dtype).itemsize,
                                 offset=aoff).reshape(shape).copy()
        if isinstance(o, tuple):
            return tuple(rebuild(x) for x in o)
        if isinstance(o, list):
            return [rebuild(x) for x in o]
        if isinstance(o, dict):
            return {k: rebuild(v) for k, v in o.items()}
        return o

    return rebuild(skeleton)


class ShmChannel:
    """Process-shared bounded queue of python batches over the C++ ring."""

    def __init__(self, name: str = None, capacity_mb: int = 64,
                 create: bool = True):
        self._lib = load_native()
        self.name = name or f"/pdtpu_q_{os.getpid()}_{id(self) & 0xFFFF}"
        if create:
            self._h = self._lib.pd_shmq_create(self.name.encode(),
                                               capacity_mb * 1024 * 1024)
        else:
            self._h = self._lib.pd_shmq_open(self.name.encode())
        if not self._h:
            raise RuntimeError(f"shm queue {'create' if create else 'open'} "
                               f"failed for {self.name}")
        self._owner = create

    def open_in_child(self) -> "ShmChannel":
        return ShmChannel(self.name, create=False)

    def put(self, obj: Any, timeout: float = 300.0) -> None:
        data = _pack(obj)
        rc = self._lib.pd_shmq_push(self._h, data, len(data), timeout)
        if rc == 1:
            raise TimeoutError("shm queue full")
        if rc == -2:
            raise BrokenPipeError("shm queue closed")
        if rc != 0:
            raise RuntimeError(f"shm push failed (batch {len(data)} bytes "
                               f"exceeds ring capacity?)")

    def get(self, timeout: float = 300.0) -> Any:
        out = ctypes.POINTER(ctypes.c_char)()
        n = self._lib.pd_shmq_pop(self._h, ctypes.byref(out), timeout)
        if n == -2:
            raise TimeoutError("shm queue empty")
        if n == -3:
            raise EOFError("shm queue closed and drained")
        if n < 0:
            raise RuntimeError("shm pop failed")
        buf = ctypes.string_at(out, n)
        self._lib.pd_shmq_free(out)
        return _unpack(buf)

    def qsize(self) -> int:
        return int(self._lib.pd_shmq_count(self._h))

    def close_writers(self):
        self._lib.pd_shmq_close_writers(self._h)

    def close(self):
        if self._h:
            self._lib.pd_shmq_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # pdlint: disable=silent-exception -- interpreter teardown: ctypes/logging may already be gone
            pass
