"""Dataset types (reference python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence


class Dataset:
    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError(f"{type(self).__name__} must implement __getitem__")

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError(f"{type(self).__name__} must implement __len__")


class IterableDataset(Dataset):
    def __iter__(self):  # pragma: no cover - abstract
        raise NotImplementedError(f"{type(self).__name__} must implement __iter__")

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..tensor_class import unwrap

        lengths = {t.shape[0] if hasattr(t, "shape") else len(t) for t in tensors}
        assert len(lengths) == 1, "all tensors must have the same first dimension"
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return t.shape[0] if hasattr(t, "shape") else len(t)


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert len({len(d) for d in self.datasets}) == 1

    def __getitem__(self, index):
        out = []
        for d in self.datasets:
            sample = d[index]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __len__(self):
        return self.cumulative_sizes[-1] if self.cumulative_sizes else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import numpy as np

    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        counts = [int(np.floor(n * l)) for l in lengths]
        rem = n - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    assert sum(lengths) == len(dataset)
    perm = np.random.permutation(len(dataset)).tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset : offset + l]))
        offset += l
    return out
