"""DataLoader.

Reference parity: python/paddle/io/DataLoader (+ dataloader_iter.py,
worker.py): single-process and multi-process iteration, default collate to
batched tensors, worker_init_fn, prefetch.

TPU-native notes: workers produce numpy batches via a fork-context
multiprocessing.Pool; conversion to device arrays happens in the consumer so
workers never touch jax (forked children must not use device state).
Prefetching = pool imap with a lookahead window, which plays the role of the
reference's _prefetch_factor queue.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference collate.py)."""
    from ..tensor_class import Tensor, wrap
    import jax.numpy as jnp

    sample = batch[0]
    if isinstance(sample, Tensor):
        return wrap(jnp.stack([s._array for s in batch]))
    if isinstance(sample, np.ndarray):
        return wrap(jnp.asarray(np.stack(batch)))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return wrap(jnp.asarray(np.asarray(batch)))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn(list(items)) for items in zip(*batch))
    return list(batch)


def _np_collate(batch):
    """Worker-side collate: numpy only (pickle-friendly, no jax in workers)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _np_collate([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(_np_collate(list(items)) for items in zip(*batch))
    return list(batch)


def _to_tensors(obj):
    from ..tensor_class import wrap
    import jax.numpy as jnp

    if isinstance(obj, np.ndarray):
        return wrap(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _to_tensors(v) for k, v in obj.items()}
    if isinstance(obj, (tuple, list)):
        return type(obj)(_to_tensors(v) for v in obj)
    return obj


# Worker-process globals: the dataset/collate are shipped ONCE via the pool
# initializer (not per task), and worker_init_fn runs once per worker.
_worker_state: dict = {}


def _pool_worker_init(dataset, collate_fn, worker_init_fn, num_workers):
    import multiprocessing as mp

    proc = mp.current_process()
    wid = (proc._identity[0] - 1) % num_workers if proc._identity else 0
    _worker_state["dataset"] = dataset
    _worker_state["collate_fn"] = collate_fn
    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)


def _pool_worker_task(indices):
    dataset = _worker_state["dataset"]
    collate_fn = _worker_state["collate_fn"]
    samples = [dataset[i] for i in indices]
    if collate_fn is not None:
        return collate_fn(samples)
    return _np_collate([_as_numpy_sample(s) for s in samples])


def _as_numpy_sample(s):
    from ..tensor_class import Tensor

    if isinstance(s, Tensor):
        return s.numpy()
    if isinstance(s, dict):
        return {k: _as_numpy_sample(v) for k, v in s.items()}
    if isinstance(s, (tuple, list)):
        return type(s)(_as_numpy_sample(v) for v in s)
    return s


def _shm_worker_loop(chan_name, task_q, dataset, collate_fn, worker_init_fn,
                     wid, num_workers):
    """Worker body for the shared-memory transport: pull index batches,
    build numpy batches, push them into the C++ shm ring (worker.py
    _worker_loop parity; the ring replaces the pickle pipe)."""
    from .shm_channel import ShmChannel

    chan = ShmChannel(chan_name, create=False)
    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    try:
        while True:
            item = task_q.get()
            if item is None:
                break
            seq, indices = item
            samples = [dataset[i] for i in indices]
            if collate_fn is not None:
                batch = _as_numpy_sample(collate_fn(samples))
            else:
                batch = _np_collate([_as_numpy_sample(s) for s in samples])
            chan.put((seq, batch))
    finally:
        chan.close()


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self._pool = None
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(dataset=dataset, shuffle=shuffle,
                                                  batch_size=batch_size, drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if self.batch_size is not None and len(batch) == self.batch_size:
                yield self._collate(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self._collate(batch)

    def _collate(self, samples):
        if self.collate_fn is not None:
            return self.collate_fn(samples)
        return default_collate_fn(samples)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self._collate([self.dataset[i]])
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                samples = [self.dataset[i] for i in indices]
                yield self._collate(samples)
            return
        if self.use_shared_memory:
            # Probe channel creation HERE so only "native core unavailable"
            # falls back to the pool; a RuntimeError raised mid-iteration
            # (worker crash) must propagate — falling back after batches
            # were already yielded would silently duplicate the epoch.
            try:
                from .shm_channel import ShmChannel

                chan = ShmChannel(capacity_mb=64)
            except RuntimeError:
                chan = None  # native core unavailable → pipe-based pool below
            if chan is not None:
                yield from self._iter_multiprocess_shm(chan)
                return
        # multiprocess path: pool imap with prefetch lookahead. Dataset +
        # collate_fn ship once per worker via the initializer; only index
        # lists cross per batch. A user collate_fn runs worker-side (must be
        # picklable, as in the reference). Fork context: workers do numpy
        # work only — do not touch jax/device state inside Dataset code.
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        with ctx.Pool(
            self.num_workers,
            initializer=_pool_worker_init,
            initargs=(self.dataset, self.collate_fn, self.worker_init_fn, self.num_workers),
        ) as pool:
            for np_batch in pool.imap(_pool_worker_task, self.batch_sampler, chunksize=1):
                yield _to_tensors(np_batch)

    def _iter_multiprocess_shm(self, chan):
        """Shared-memory transport: workers push packed numpy batches into
        the native C++ ring (io/shm_channel.py); batches re-order by
        sequence id here (the reference's _order outstanding-batch cache).
        ``chan`` is created by the caller so creation failure (no native
        core) can fall back without masking mid-iteration worker crashes."""
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        task_q = ctx.Queue()
        procs = [
            ctx.Process(
                target=_shm_worker_loop,
                args=(chan.name, task_q, self.dataset, self.collate_fn,
                      self.worker_init_fn, wid, self.num_workers),
                daemon=True)
            for wid in range(self.num_workers)
        ]
        try:
            for p in procs:
                p.start()
            expected = 0
            for seq, indices in enumerate(self.batch_sampler):
                task_q.put((seq, list(indices)))
                expected += 1
            for _ in procs:
                task_q.put(None)
            buffer = {}
            next_seq = 0
            timeout = self.timeout  # paddle semantics: 0/None = wait forever
            last_progress = time.monotonic()
            while next_seq < expected:
                if next_seq in buffer:
                    yield _to_tensors(buffer.pop(next_seq))
                    next_seq += 1
                    last_progress = time.monotonic()
                    continue
                try:
                    seq, batch = chan.get(timeout=5.0)
                except TimeoutError:
                    if not any(p.is_alive() for p in procs) and \
                            chan.qsize() == 0:
                        raise RuntimeError(
                            "DataLoader shm workers exited before producing "
                            "all batches (worker crash?)") from None
                    if timeout and time.monotonic() - last_progress > timeout:
                        raise TimeoutError(
                            f"DataLoader timed out: no batch for "
                            f"{timeout:.0f}s (stuck worker?)") from None
                    continue
                buffer[seq] = batch
                last_progress = time.monotonic()
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join()
            chan.close()
