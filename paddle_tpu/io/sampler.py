"""Samplers (reference python/paddle/io/dataloader/{sampler,batch_sampler}.py).

DistributedBatchSampler (batch_sampler.py:192) shards the index space across
data-parallel ranks with padding + per-epoch shuffling — identical semantics
here, with rank/world sourced from distributed.env when not given.
"""
from __future__ import annotations

import math

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, size=self.num_samples).tolist())
        perm = np.random.permutation(n).tolist()
        return iter(perm[: self.num_samples])

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray([float(w) for w in weights])
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), size=self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        super().__init__(dataset)
        assert (dataset is None) != (sampler is None), "provide exactly one of dataset/sampler"
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference batch_sampler.py:192: pad to world_size, shard contiguous
    per-rank slices, reshuffle per epoch via set_epoch."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as _env

            num_replicas = num_replicas if num_replicas is not None else _env.get_world_size()
            rank = rank if rank is not None else _env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n).tolist()
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        # pad to make evenly divisible
        indices += indices[: (self.total_size - n)]
        assert len(indices) == self.total_size
        # contiguous per-rank subsample (reference behavior)
        local = indices[self.local_rank * self.num_samples : (self.local_rank + 1) * self.num_samples]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
