"""nn layer round-3 tail: the remaining reference nn.__all__ classes
(python/paddle/nn/__init__.py) — thin Layer wrappers over the functional
tail in functional/extra.py, plus generic RNN/BiRNN runners, seq2seq
dynamic decoding, ParameterDict, and AdaptiveLogSoftmaxWithLoss.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor_class import Tensor, unwrap, wrap
from .layer import Layer
from .initializer_core import Uniform
from . import functional as F


# ---------------------------------------------------------------------------
# functional wrappers
# ---------------------------------------------------------------------------

class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p
        self.training = True

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, self.training)


class Unfold(Layer):
    """nn.Unfold (im2col) over F.unfold."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        k, s, p, d = self._args
        return F.unfold(x, k, strides=s, paddings=p, dilations=d)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        o, k, s, p, d = self._args
        return F.fold(x, o, k, s, p, d)


class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        k, s, p, o, fmt = self._args
        return F.max_unpool1d(x, indices, k, s, p, o, fmt)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self._args
        return F.max_unpool2d(x, indices, k, s, p, o)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        k, s, p, o, fmt = self._args
        return F.max_unpool3d(x, indices, k, s, p, o, fmt)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, m = self._args
        return F.fractional_max_pool2d(x, o, k, u, m)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, m = self._args
        return F.fractional_max_pool3d(x, o, k, u, m)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, padding, ceil_mode,
                      data_format)

    def forward(self, x):
        return F.lp_pool1d(x, *self._args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, padding, ceil_mode,
                      data_format)

    def forward(self, x):
        return F.lp_pool2d(x, *self._args)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = (padding, padding) if isinstance(padding, int) \
            else tuple(padding)
        self.data_format = data_format

    def forward(self, x):
        l, r = self.padding

        def fn(a):
            if self.data_format == "NCL":
                return jnp.pad(a, ((0, 0), (0, 0), (l, r)))
            return jnp.pad(a, ((0, 0), (l, r), (0, 0)))

        from ..ops.registry import apply

        return apply("zeropad1d", fn, x)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = (padding,) * 6 if isinstance(padding, int) \
            else tuple(padding)
        self.data_format = data_format

    def forward(self, x):
        l, r, tp, bo, fr, bk = self.padding

        def fn(a):
            if self.data_format == "NCDHW":
                return jnp.pad(a, ((0, 0), (0, 0), (fr, bk), (tp, bo), (l, r)))
            return jnp.pad(a, ((0, 0), (fr, bk), (tp, bo), (l, r), (0, 0)))

        from ..ops.registry import apply

        return apply("zeropad3d", fn, x)


# ---------------------------------------------------------------------------
# loss wrappers
# ---------------------------------------------------------------------------

class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self._args)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, *self._args)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (p, margin, weight, reduction)

    def forward(self, input, label):
        p, m, w, r = self._args
        return F.multi_margin_loss(input, label, p, m, w, r)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._args = (weight, reduction)

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, *self._args)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, *self._args)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (blank, fastemit_lambda, reduction)

    def forward(self, logits, labels, input_lengths, label_lengths):
        b, f, r = self._args
        return F.rnnt_loss(logits, labels, input_lengths, label_lengths,
                           b, f, r)


class HSigmoidLoss(Layer):
    """nn.HSigmoidLoss (hierarchical sigmoid, python/paddle/nn/layer/loss.py):
    owns the internal-node weight table."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        std = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size],
            default_initializer=Uniform(-std, std))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_classes - 1], is_bias=True,
            default_initializer=Uniform(-std, std))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """nn.AdaptiveLogSoftmaxWithLoss (Grave et al. adaptive softmax)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.shortlist = self.cutoffs[0]
        n_clusters = len(self.cutoffs) - 1
        self.head_weight = self.create_parameter(
            [in_features, self.shortlist + n_clusters])
        self.head_bias = self.create_parameter(
            [self.shortlist + n_clusters], is_bias=True) if head_bias else None
        self.tail_weights = []
        for k in range(n_clusters):
            hsz = max(1, int(in_features / (div_value ** (k + 1))))
            osz = self.cutoffs[k + 1] - self.cutoffs[k]
            proj = self.create_parameter([in_features, hsz])
            cls = self.create_parameter([hsz, osz])
            setattr(self, f"_tail_proj_{k}", proj)
            setattr(self, f"_tail_cls_{k}", cls)
            self.tail_weights.append((proj, cls))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights, self.cutoffs,
            self.head_bias)

    def log_prob(self, input):
        """Full [batch, n_classes] log-probabilities."""
        head = unwrap(input) @ unwrap(self.head_weight)
        if self.head_bias is not None:
            head = head + unwrap(self.head_bias)
        head_lp = jax.nn.log_softmax(head, -1)
        parts = [head_lp[:, : self.shortlist]]
        for k, (proj, cls) in enumerate(self.tail_weights):
            tail_lp = jax.nn.log_softmax(
                (unwrap(input) @ unwrap(proj)) @ unwrap(cls), -1)
            parts.append(head_lp[:, self.shortlist + k][:, None] + tail_lp)
        return wrap(jnp.concatenate(parts, -1))

    def predict(self, input):
        return wrap(jnp.argmax(unwrap(self.log_prob(input)), -1))


# ---------------------------------------------------------------------------
# generic RNN runners
# ---------------------------------------------------------------------------

class RNN(Layer):
    """nn.RNN (python/paddle/nn/layer/rnn.py RNN): run any cell over time.
    time_major=False → inputs [batch, time, ...]."""

    def __init__(self, cell, is_reverse=False, time_major=False, name=None):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        axis = 0 if self.time_major else 1
        steps = unwrap(inputs).shape[axis]
        idx = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        seq_len = None
        if sequence_length is not None:
            seq_len = jnp.asarray(unwrap(sequence_length)).reshape(-1)
        outs = []
        states = initial_states
        if states is None and seq_len is not None and \
                hasattr(self.cell, "get_initial_states"):
            # masking needs a concrete state to freeze into from step one
            # (matters for is_reverse, where padding is visited first)
            from .rnn import LSTMCell

            first = inputs[:, 0] if axis == 1 else inputs[0]
            init = self.cell.get_initial_states(first)
            states = (init, init) if isinstance(self.cell, LSTMCell) else init
        for t in idx:
            x_t = inputs[:, t] if axis == 1 else inputs[t]
            out, new_states = self.cell(x_t, states)
            if seq_len is not None and states is not None:
                # freeze state and zero output past each sample's length
                # (reference RNN masks by sequence_length)
                active = (seq_len > t).astype(unwrap(out).dtype)[:, None]
                out = wrap(unwrap(out) * active)
                is_t = lambda v: isinstance(v, Tensor)
                new_l, treedef = jax.tree_util.tree_flatten(
                    new_states, is_leaf=is_t)
                old_l = jax.tree_util.tree_leaves(states, is_leaf=is_t)
                mixed = [wrap(unwrap(n) * active + unwrap(o) * (1 - active))
                         for n, o in zip(new_l, old_l)]
                states = jax.tree_util.tree_unflatten(treedef, mixed)
            else:
                states = new_states
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        from ..ops.manipulation import stack

        return stack(outs, axis=axis), states


class BiRNN(Layer):
    """nn.BiRNN: forward + backward cells, concatenated features."""

    def __init__(self, cell_fw, cell_bw, time_major=False, name=None):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, fw_states = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, bw_states = self.rnn_bw(inputs, st_bw, sequence_length)
        from ..ops.manipulation import concat

        return concat([out_fw, out_bw], axis=-1), (fw_states, bw_states)


# ---------------------------------------------------------------------------
# seq2seq decoding
# ---------------------------------------------------------------------------

class BeamSearchDecoder(Layer):
    """nn.BeamSearchDecoder (python/paddle/nn/decode.py): beam search over a
    cell + embedding + output head."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        super().__init__()
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, out):
        return self.output_fn(out) if self.output_fn is not None else out


def dynamic_decode(decoder, inits=None, max_step_num=20, **kwargs):
    """nn.dynamic_decode: greedy-within-beam decoding loop (host loop, each
    step jit-compiled through the cell). Returns (ids [B, T, beam],
    final log-probs [B, beam])."""
    beam = decoder.beam_size
    cell_state = inits
    # first step: start tokens
    b_ref = None
    tok = None
    ids_steps = []
    log_probs = None
    state = cell_state
    for step in range(max_step_num):
        if tok is None:
            # bootstrap: single start token per batch item
            emb_in = decoder.embedding_fn(decoder.start_token) \
                if decoder.embedding_fn else decoder.start_token
            out, state = decoder.cell(emb_in, state)
            logits = decoder._logits(out)
            lp = jax.nn.log_softmax(unwrap(logits), -1)
            b = lp.shape[0]
            top_lp, top_ids = jax.lax.top_k(lp, beam)     # [B, beam]
            log_probs = top_lp
            tok = top_ids
            ids_steps.append(top_ids)
            # tile state per beam
            state = jax.tree_util.tree_map(
                lambda s: jnp.repeat(unwrap(s), beam, axis=0), state)
            continue
        flat_tok = wrap(unwrap(tok).reshape(-1))           # [B*beam]
        emb_in = decoder.embedding_fn(flat_tok) if decoder.embedding_fn \
            else flat_tok
        out, state = decoder.cell(emb_in, state)
        logits = decoder._logits(out)
        lp = jax.nn.log_softmax(unwrap(logits), -1)        # [B*beam, V]
        V = lp.shape[-1]
        b = unwrap(tok).shape[0]
        total = log_probs[..., None] + lp.reshape(b, beam, V)
        flat = total.reshape(b, beam * V)
        top_lp, flat_ids = jax.lax.top_k(flat, beam)
        beam_src = flat_ids // V
        new_tok = flat_ids % V
        log_probs = top_lp
        tok = new_tok
        # reorder beams in the recorded history
        ids_steps = [jnp.take_along_axis(s, beam_src, axis=1)
                     for s in ids_steps]
        ids_steps.append(new_tok)
        # reorder cell state rows to follow surviving beams
        gather_rows = (jnp.arange(b)[:, None] * beam + beam_src).reshape(-1)
        state = jax.tree_util.tree_map(
            lambda s: unwrap(s)[gather_rows], state)
        if bool((new_tok == decoder.end_token).all()):
            break
    ids = jnp.stack(ids_steps, axis=1)                     # [B, T, beam]
    return wrap(ids), wrap(log_probs)


# ---------------------------------------------------------------------------
# containers / clip re-exports
# ---------------------------------------------------------------------------

class ParameterDict(Layer):
    """nn.ParameterDict (container.py ParameterDict)."""

    def __init__(self, parameters=None):
        super().__init__()
        self._keys = []
        if parameters:
            for k, v in (parameters.items()
                         if isinstance(parameters, dict) else parameters):
                self[k] = v

    def __setitem__(self, key, param):
        self._keys.append(key) if key not in self._keys else None
        self.add_parameter(str(key), param)

    def __getitem__(self, key):
        return getattr(self, str(key))

    def __contains__(self, key):
        return key in self._keys

    def __len__(self):
        return len(self._keys)

    def keys(self):
        return list(self._keys)

    def items(self):
        return [(k, self[k]) for k in self._keys]

    def values(self):
        return [self[k] for k in self._keys]

    def update(self, parameters):
        for k, v in (parameters.items()
                     if isinstance(parameters, dict) else parameters):
            self[k] = v
