"""nn.utils: weight norm / spectral norm wrappers, vector<->parameters.

Reference parity: python/paddle/nn/utils/.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor_class import Tensor, wrap, unwrap


def parameters_to_vector(parameters, name=None):
    return wrap(jnp.concatenate([unwrap(p).reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    arr = unwrap(vec)
    offset = 0
    for p in parameters:
        n = p.size
        p._array = arr[offset : offset + n].reshape(p._array.shape).astype(p.dtype)
        offset += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return wrap(jnp.zeros(()))
    total = jnp.sqrt(sum(jnp.sum(jnp.square(unwrap(g))) for g in grads))
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._array = unwrap(p.grad) * clip_coef
    return wrap(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._array = jnp.clip(unwrap(p.grad), -clip_value, clip_value)


def weight_norm(layer, name="weight", dim=0):
    """Re-parameterise weight = g * v / ||v|| (reference nn/utils/weight_norm_hook.py)."""
    from .layer import Layer
    from ..tensor_class import Parameter

    w = getattr(layer, name)
    arr = unwrap(w)
    if dim is None:
        norm = jnp.linalg.norm(arr)
    else:
        axes = tuple(i for i in range(arr.ndim) if i != dim)
        norm = jnp.sqrt(jnp.sum(jnp.square(arr), axis=axes, keepdims=False))
    g = Parameter(norm)
    v = Parameter(arr)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def hook(l, inputs):
        from ..ops.registry import apply

        def fn(varr, garr):
            if dim is None:
                return garr * varr / jnp.linalg.norm(varr)
            axes = tuple(i for i in range(varr.ndim) if i != dim)
            nrm = jnp.sqrt(jnp.sum(jnp.square(varr), axis=axes, keepdims=True))
            shape = [1] * varr.ndim
            shape[dim] = -1
            return garr.reshape(shape) * varr / nrm

        # recorded on the tape → gradients flow back to weight_v / weight_g
        l.__dict__[name] = apply("weight_norm", fn,
                                 l._parameters[name + "_v"], l._parameters[name + "_g"])

    layer._wn_hook = layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    hook = getattr(layer, "_wn_hook", None)
    if hook is not None:
        hook.remove()
    from ..tensor_class import Parameter

    w = layer.__dict__.pop(name, None)
    if w is not None:
        layer.add_parameter(name, Parameter(unwrap(w)))
    for k in (name + "_g", name + "_v"):
        layer._parameters.pop(k, None)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    from .layers_common import SpectralNorm as _SN

    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(tuple(unwrap(w).shape), dim=dim, power_iters=n_power_iterations, epsilon=eps)
    layer.add_sublayer(name + "_sn", sn)

    def hook(l, inputs):
        w_orig = l._parameters.get(name + "_orig")
        l.__dict__[name] = sn(w_orig)

    if name in layer._parameters:
        layer.add_parameter(name + "_orig", layer._parameters.pop(name))
    layer._sn_hook = layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer


def replace_sublayers(model, match_fn, build_fn):
    """Recursive IN-PLACE sublayer replacement: wherever
    ``match_fn(attr_name, sublayer)`` is True, install
    ``build_fn(sublayer)`` in its place (the matched subtree is not
    descended into). Returns the replacement count.

    The one traversal shared by the model-surgery passes
    (nn.quant.quantize_for_serving, peft.get_peft_model/merge_lora).
    """
    n = 0

    def visit(layer):
        nonlocal n
        for name, sub in list(layer._sub_layers.items()):
            if sub is None:
                continue
            if match_fn(name, sub):
                layer._sub_layers[name] = build_fn(sub)
                n += 1
            else:
                visit(sub)

    visit(model)
    return n
