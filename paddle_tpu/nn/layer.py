"""paddle_tpu.nn.Layer — the module system.

Reference parity: paddle.nn.Layer (python/paddle/nn/layer/layers.py): named
parameter/buffer/sublayer trees, hooks, state_dict semantics, train/eval,
to()/astype. TPU-native additions: ``functional_state`` /
``load_functional_state`` produce/consume a pure pytree of arrays so any
Layer drops into jax.jit/jax.grad/pjit (the role the dygraph→static
translators play in the reference, without AST surgery), and
``shard_fn``-style placement annotations hang off parameters for the
auto-parallel API (distributed/api.py).
"""
from __future__ import annotations

import collections
import contextlib
import threading
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import numpy as np

from ..tensor_class import Tensor, Parameter, wrap, unwrap
from ..framework import dtype as _dtype_mod
from .initializer_core import _resolve_initializer, ParamAttr


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id[0]
        HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)


#: process-wide trace serializer: ``functional_weights`` swaps TRACER
#: arrays into the layer's parameters for the duration of a jit trace,
#: so two threads tracing against the same model concurrently would
#: read each other's tracers (the serving engine compiling a prefill
#: while the correctness sentinel's audit worker retraces the reference
#: decode path). The traced body only runs at TRACE time — compiled
#: executions never enter this context — so the decode hot path never
#: contends here. Reentrant: a traced body that traces an inner jitted
#: step on the same thread re-enters freely.
_TRACE_LOCK = threading.RLock()


@contextlib.contextmanager
def functional_weights(layer, state):
    """Temporarily install a functional parameter pytree on ``layer`` inside
    a trace, restoring the original arrays after — the shared spine of every
    jitted step (TrainStep, pipeline stage fns, serving prefill/decode).
    Yields the layer's live state_dict so callers can read in-trace buffer
    updates (BatchNorm stats) before the restore. Cross-thread traces
    serialize on :data:`_TRACE_LOCK` — the parameter swap is a mutation
    of shared model state."""
    with _TRACE_LOCK:
        own = layer.state_dict()
        snapshot = {k: t._array for k, t in own.items()}
        layer.load_functional_state(state)
        try:
            yield own
        finally:
            for k, t in own.items():
                t._array = snapshot[k]


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        # reference semantics (python/paddle/nn/layer/layers.py): a Layer
        # with no explicit dtype uses the GLOBAL default dtype, so model
        # code under framework.dtype_guard("bfloat16") builds bf16 params
        self._dtype = (_dtype_mod.convert_dtype(dtype) if dtype is not None
                       else _dtype_mod.default_float_dtype())
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, Optional["Layer"]] = collections.OrderedDict()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._name_scope = name_scope or type(self).__name__.lower()

    # ---- attribute routing ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
            buffers.pop(name, None) if buffers else None
            layers.pop(name, None) if layers else None
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name] = Parameter.from_tensor(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            buffers[name] = value if (value is None or isinstance(value, Tensor)) else wrap(jax.numpy.asarray(value))
        elif layers is not None and name in layers and value is None:
            layers[name] = None
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._buffers) + list(self._sub_layers)

    # ---- construction helpers ------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        """Reference: Layer.create_parameter (layers.py) — honors ParamAttr
        (initializer/trainable/name)."""
        if attr is False:
            return None
        dtype = _dtype_mod.convert_dtype(dtype) if dtype is not None else self._dtype
        attr = ParamAttr._to_attr(attr)
        init = _resolve_initializer(attr, default_initializer, is_bias)
        arr = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(arr, trainable=attr.trainable if attr else True,
                      name=attr.name if attr else None)
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        t = wrap(jax.numpy.zeros((), dtype=_dtype_mod.convert_dtype(dtype) if dtype else self._dtype))
        t.persistable = persistable
        return t

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # ---- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ---- traversal -----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer, in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{name}.{pname}" if name else pname), p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def _traverse(self, prefix="", include_sublayers=True):
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, True)

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, sub
            yield from sub.named_sublayers(sub_prefix)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return (l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return ((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ---- modes ---------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ---- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self._traverse(structured_name_prefix.rstrip("."), include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    dest[(f"{name}.{bname}" if name else bname)] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for key, value in state_dict.items():
            if key in own:
                arr = value._array if isinstance(value, Tensor) else jax.numpy.asarray(np.asarray(value))
                target = own[key]
                if tuple(arr.shape) != tuple(target._array.shape):
                    raise ValueError(
                        f"shape mismatch for {key}: loading {tuple(arr.shape)} into {tuple(target._array.shape)}"
                    )
                target._array = arr.astype(target._array.dtype)
                matched.add(key)
            else:
                unexpected.append(key)
        missing = [k for k in own if k not in matched]
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---- dtype / device movement --------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        from ..framework import device as _device_mod

        dev = _device_mod._resolve(device) if device is not None else None
        dt = _dtype_mod.convert_dtype(dtype) if dtype is not None else None
        for _, p in list(self.named_parameters()) + list(self.named_buffers()):
            arr = p._array
            if dt is not None and _dtype_mod.is_floating_point_dtype(arr.dtype):
                arr = arr.astype(dt)
            if dev is not None:
                arr = jax.device_put(arr, dev)
            p._array = arr
        if dt is not None:
            self._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ---- functional bridge (jit / pjit path) ---------------------------------
    def functional_state(self) -> Dict[str, Any]:
        """Pure pytree {name: jax.Array} of all parameters + buffers.
        Serializes on :data:`_TRACE_LOCK`: while another thread's trace
        is inside :func:`functional_weights` the parameters hold that
        trace's TRACERS, and a concurrent snapshot would capture (and
        leak) them instead of real arrays."""
        with _TRACE_LOCK:
            return {k: v._array for k, v in self.state_dict().items()}

    def load_functional_state(self, state: Dict[str, Any]):
        own = self.state_dict()
        for k, arr in state.items():
            if k in own:
                own[k]._array = arr
        return self

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            mod_str = repr(sub)
            mod_str = "\n".join("  " + l for l in mod_str.split("\n"))
            lines.append(f"({name}): {mod_str.strip()}" if "\n" not in mod_str else f"({name}): {mod_str.lstrip()}")
        main = f"{type(self).__name__}({extra}"
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
