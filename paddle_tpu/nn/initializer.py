"""paddle_tpu.nn.initializer namespace (reference python/paddle/nn/initializer/)."""
from .initializer_core import (
    Initializer,
    Constant,
    Normal,
    TruncatedNormal,
    Uniform,
    XavierNormal,
    XavierUniform,
    KaimingNormal,
    KaimingUniform,
    Assign,
    Orthogonal,
    Dirac,
    calculate_gain,
)

# paddle also exposes lowercase aliases in nn.initializer
constant = Constant
normal = Normal
uniform = Uniform


class Bilinear(Initializer):
    """nn.initializer.Bilinear (python/paddle/nn/initializer/Bilinear):
    bilinear-interpolation upsampling kernels for transposed conv weights
    [C_out, C_in, K, K]."""

    def __call__(self, shape, dtype="float32"):
        import numpy as np

        import jax.numpy as jnp

        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D weight")
        k = shape[-1]
        factor = (k + 1) // 2
        center = factor - 1.0 if k % 2 == 1 else factor - 0.5
        og = np.ogrid[:k, :k]
        filt = ((1 - np.abs(og[0] - center) / factor)
                * (1 - np.abs(og[1] - center) / factor))
        w = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            w[i, i % shape[1]] = filt
        from ..framework.dtype import convert_dtype

        return jnp.asarray(w, convert_dtype(dtype))


_GLOBAL_WEIGHT_INIT = None
_GLOBAL_BIAS_INIT = None


def set_global_initializer(weight_init, bias_init=None):
    """nn.initializer.set_global_initializer: default initializers used by
    Layer.create_parameter when no explicit attr/initializer is given."""
    global _GLOBAL_WEIGHT_INIT, _GLOBAL_BIAS_INIT
    _GLOBAL_WEIGHT_INIT = weight_init
    _GLOBAL_BIAS_INIT = bias_init


def _global_initializer(is_bias: bool):
    return _GLOBAL_BIAS_INIT if is_bias else _GLOBAL_WEIGHT_INIT
