"""paddle_tpu.nn.initializer namespace (reference python/paddle/nn/initializer/)."""
from .initializer_core import (
    Initializer,
    Constant,
    Normal,
    TruncatedNormal,
    Uniform,
    XavierNormal,
    XavierUniform,
    KaimingNormal,
    KaimingUniform,
    Assign,
    Orthogonal,
    Dirac,
    calculate_gain,
)

# paddle also exposes lowercase aliases in nn.initializer
constant = Constant
normal = Normal
uniform = Uniform
