"""paddle_tpu.nn — layers, functional, initializers.

Reference parity: python/paddle/nn/__init__.py.
"""
from . import functional
from . import initializer
from .layer import Layer, HookRemoveHelper
from .initializer_core import ParamAttr
from .container import Sequential, LayerList, LayerDict, ParameterList
from .layers_common import (
    Linear, Identity, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
    AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    Flatten, Unflatten, Pad1D, Pad2D, Pad3D, ZeroPad2D, PixelShuffle,
    PixelUnshuffle, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    CosineSimilarity, Bilinear,
)
from .layers_act_loss import (
    ReLU, ReLU6, GELU, SiLU, Swish, Mish, ELU, SELU, CELU, LeakyReLU,
    Hardshrink, Hardsigmoid, Hardswish, Hardtanh, LogSigmoid, LogSoftmax,
    Softmax, Softmax2D, Softplus, Softshrink, Softsign, Tanh, Tanhshrink,
    ThresholdedReLU, Sigmoid, GLU, RReLU, Maxout, PReLU,
    CrossEntropyLoss, MSELoss, L1Loss, SmoothL1Loss, HuberLoss, BCELoss,
    BCEWithLogitsLoss, KLDivLoss, NLLLoss, MarginRankingLoss,
    HingeEmbeddingLoss, CosineEmbeddingLoss, TripletMarginLoss, CTCLoss,
)
from .transformer import (
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .rnn import SimpleRNN, LSTM, GRU, RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell
from .layers_extra import (
    PairwiseDistance, FeatureAlphaDropout, Unfold, Fold, Silu,
    ChannelShuffle, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    FractionalMaxPool2D, FractionalMaxPool3D, LPPool1D, LPPool2D,
    ZeroPad1D, ZeroPad3D, PoissonNLLLoss, GaussianNLLLoss, SoftMarginLoss,
    MultiMarginLoss, MultiLabelSoftMarginLoss, TripletMarginWithDistanceLoss,
    RNNTLoss, HSigmoidLoss, AdaptiveLogSoftmaxWithLoss, RNN, BiRNN,
    BeamSearchDecoder, dynamic_decode, ParameterDict,
)
# gradient clipping lives with the optimizers; the reference also exports it
# under paddle.nn (python/paddle/nn/__init__.py ClipGradBy*)
from ..optimizer.clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm
from ..tensor_class import Parameter


def __getattr__(name):
    # lazy submodule access (paddle.nn.utils / paddle.nn.quant / ...) via
    # importlib, NOT `from . import x`: the fromlist machinery re-enters
    # this __getattr__ before the submodule attribute is set, recursing
    # forever
    if name.startswith("_"):
        raise AttributeError(f"module 'paddle_tpu.nn' has no attribute {name!r}")
    import importlib

    full = __name__ + "." + name
    try:
        mod = importlib.import_module(full)
    except ImportError as e:
        if e.name != full:
            raise  # a REAL dependency failure inside an existing submodule
        raise AttributeError(
            f"module 'paddle_tpu.nn' has no attribute {name!r}") from None
    globals()[name] = mod
    return mod
