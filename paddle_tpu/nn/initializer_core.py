"""Initializers + ParamAttr.

Reference parity: python/paddle/nn/initializer/ (Constant, Normal,
TruncatedNormal, Uniform, Xavier*, Kaiming*, Assign, Orthogonal, Dirac) and
paddle.ParamAttr (python/paddle/base/param_attr.py). Each initializer is a
callable (shape, dtype) -> jax.Array drawing from framework/random.py.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as _random
from ..framework import dtype as _dtype_mod


class Initializer:
    def __call__(self, shape, dtype):  # pragma: no cover - abstract
        raise NotImplementedError

    def _fan(self, shape):
        shape = tuple(shape)
        if len(shape) < 1:
            return 1, 1
        if len(shape) == 1:
            return shape[0], shape[0]
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[0] * receptive if len(shape) == 2 else shape[1] * receptive
        fan_out = shape[1] * receptive if len(shape) == 2 else shape[0] * receptive
        return fan_in, fan_out


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = _random.next_key()
        return (self.mean + self.std * jax.random.normal(k, shape, dtype=jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        k = _random.next_key()
        z = jax.random.truncated_normal(k, self.a, self.b, shape, dtype=jnp.float32)
        return (self.mean + self.std * z).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = _random.next_key()
        return jax.random.uniform(k, shape, dtype=jnp.float32, minval=self.low, maxval=self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = _random.next_key()
        return (std * jax.random.normal(k, shape, dtype=jnp.float32)).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = _random.next_key()
        return jax.random.uniform(k, shape, dtype=jnp.float32, minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        k = _random.next_key()
        return (std * jax.random.normal(k, shape, dtype=jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = self._fan(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        k = _random.next_key()
        return jax.random.uniform(k, shape, dtype=jnp.float32, minval=-limit, maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = jnp.asarray(np.asarray(self.value if not hasattr(self.value, "_array") else self.value._array))
        return arr.reshape(shape).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = _random.next_key()
        return (self.gain * jax.nn.initializers.orthogonal()(k, shape, jnp.float32)).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        per = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per, ic)):
                arr[(g * per + i, i) + mid] = 1.0
        return jnp.asarray(arr).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
             "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
             "selu": 3.0 / 4}
    return gains[nonlinearity]


class ParamAttr:
    """paddle.ParamAttr parity (initializer/trainable/learning_rate/name)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None or attr is True:
            return ParamAttr()
        if attr is False:
            return None
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")


def _resolve_initializer(attr, default_initializer, is_bias):
    if attr is not None and attr.initializer is not None:
        return attr.initializer
    # set_global_initializer overrides built-in layer defaults (reference
    # semantics: only an explicit ParamAttr initializer beats the global)
    try:
        from .initializer import _global_initializer

        g = _global_initializer(is_bias)
        if g is not None:
            return g
    except ImportError:  # pragma: no cover - during partial package init
        pass
    if default_initializer is not None:
        return default_initializer
    return Constant(0.0) if is_bias else XavierNormal()
