"""Core nn layers.

Reference parity: python/paddle/nn/layer/{common,norm,conv,pooling,
transformer}.py. Weight layouts match the reference exactly (Linear weight is
[in, out]; Conv weight [out, in/groups, *k]) so state_dicts transfer.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .layer import Layer
from .initializer_core import (
    Constant, KaimingUniform, Normal, ParamAttr, Uniform, XavierNormal,
)
from ..tensor_class import Tensor, wrap, unwrap
from ..framework import dtype as _dtype_mod
from .functional import (
    activation as F_act,
    common as F_common,
    conv as F_conv,
    attention as F_attn,
)
from . import functional as F


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features]
    (reference python/paddle/nn/layer/common.py::Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True,
        )

    def forward(self, x):
        return F_common.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        if padding_idx is not None:
            self.weight._array = self.weight._array.at[padding_idx].set(0.0)

    def forward(self, x):
        return F_common.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F_common.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F_common.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F_common.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F_common.alpha_dropout(x, self.p, training=self.training)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F_common.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """paddle.incubate fused_rms_norm parity; Pallas-fused on TPU."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self._epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], attr=weight_attr,
                                            default_initializer=Constant(1.0))

    def forward(self, x):
        from ..ops.registry import apply
        from ..ops.pallas import fused_norm

        return apply("rms_norm", lambda a, w: fused_norm.rms_norm(a, w, self._epsilon), x, self.weight)

    def extra_repr(self):
        return f"hidden_size={self.hidden_size}, epsilon={self._epsilon}"


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self._mean = self.register_buffer("_mean", wrap(jnp.zeros(num_features, jnp.float32)))
        self._variance = self.register_buffer("_variance", wrap(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F_common.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Under GSPMD, batch stats are computed over the global (sharded) batch
    inside pjit — sync comes from the partitioner, so this is BatchNorm with
    the conversion helper for API parity."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            new.weight, new.bias = layer.weight, layer.bias
            new._mean, new._variance = layer._mean, layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F_common.group_norm(x, self._num_groups, self.weight, self.bias,
                                   self._epsilon, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        self.scale = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F_common.instance_norm(x, weight=self.scale, bias=self.bias,
                                      eps=self._epsilon, data_format=self._data_format)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F_common.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter([h], default_initializer=Normal(0, 1))
        self.weight_v = self.create_parameter([w], default_initializer=Normal(0, 1))

    def forward(self, weight):
        from ..ops.registry import apply

        def fn(w, u, v):
            mat = jnp.moveaxis(w, self._dim, 0).reshape(w.shape[self._dim], -1)
            for _ in range(self._power_iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + self._epsilon)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + self._epsilon)
            sigma = u @ mat @ v
            return w / sigma

        return apply("spectral_norm", fn, weight, self.weight_u, self.weight_v)


# ---- conv layers -------------------------------------------------------------

class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        ks = (kernel_size,) * n if isinstance(kernel_size, int) else tuple(kernel_size)
        self._in_channels, self._out_channels = in_channels, out_channels
        self._kernel_size = ks
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups, self._data_format = groups, data_format
        self._transpose, self._output_padding = transpose, output_padding
        if transpose:
            wshape = [in_channels, out_channels // groups, *ks]
        else:
            wshape = [out_channels, in_channels // groups, *ks]
        fan_in = in_channels * int(np.prod(ks)) // groups
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in, negative_slope=math.sqrt(5), nonlinearity="leaky_relu"),
        )
        bound = 1 / math.sqrt(fan_in)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound) if bias_attr is None else None,
        )

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, kernel_size={self._kernel_size}, "
                f"stride={self._stride}, padding={self._padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F_conv.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                             self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F_conv.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                             self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F_conv.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                             self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x):
        return F_conv.conv1d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                       self._output_padding, self._groups, self._dilation,
                                       data_format=self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x):
        return F_conv.conv2d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                       self._output_padding, self._groups, self._dilation,
                                       data_format=self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x):
        return F_conv.conv3d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                       self._output_padding, self._groups, self._dilation,
                                       data_format=self._data_format)


# ---- pooling layers ----------------------------------------------------------

def _make_pool_layer(fn_name, n):
    fn = getattr(F_conv, fn_name)

    class _Pool(Layer):
        def __init__(self, kernel_size=None, stride=None, padding=0, **kwargs):
            super().__init__()
            self.kernel_size = kernel_size
            self.stride = stride
            self.padding = padding
            self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

        def forward(self, x):
            return fn(x, self.kernel_size, self.stride, self.padding, **self.kwargs)

    _Pool.__name__ = "".join(p.capitalize() for p in fn_name.split("_"))
    return _Pool


AvgPool1D = _make_pool_layer("avg_pool1d", 1)
AvgPool2D = _make_pool_layer("avg_pool2d", 2)
AvgPool3D = _make_pool_layer("avg_pool3d", 3)
MaxPool1D = _make_pool_layer("max_pool1d", 1)
MaxPool2D = _make_pool_layer("max_pool2d", 2)
MaxPool3D = _make_pool_layer("max_pool3d", 3)


class _AdaptivePool(Layer):
    def __init__(self, output_size, fn, **kw):
        super().__init__()
        self.output_size = output_size
        self._fn = fn
        self._kw = kw

    def forward(self, x):
        return self._fn(x, self.output_size, **self._kw)


class AdaptiveAvgPool1D(_AdaptivePool):
    def __init__(self, output_size, name=None):
        super().__init__(output_size, F_conv.adaptive_avg_pool1d)


class AdaptiveAvgPool2D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(output_size, F_conv.adaptive_avg_pool2d, data_format=data_format)


class AdaptiveAvgPool3D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(output_size, F_conv.adaptive_avg_pool3d, data_format=data_format)


class AdaptiveMaxPool1D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, F_conv.adaptive_max_pool1d)


class AdaptiveMaxPool2D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, F_conv.adaptive_max_pool2d)


class AdaptiveMaxPool3D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, F_conv.adaptive_max_pool3d)


# ---- padding / reshaping layers ---------------------------------------------

class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..ops import manipulation

        return manipulation.flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ..ops import manipulation

        full = x.shape[: self.axis] + list(self.shape) + x.shape[self.axis + 1:]
        return manipulation.reshape(x, full)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        from ..ops import manipulation

        return manipulation.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadNd):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F_common.pixel_shuffle(x, self.factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F_common.pixel_unshuffle(x, self.factor, self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor, self.mode = size, scale_factor, mode
        self.align_corners, self.align_mode, self.data_format = align_corners, align_mode, data_format

    def forward(self, x):
        return F_common.interpolate(x, self.size, self.scale_factor, self.mode,
                                    self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, data_format=data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F_common.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features],
                                            attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        from ..ops.registry import apply

        def fn(a, b, w, *bias):
            out = jnp.einsum("bi,oij,bj->bo", a, w, b)
            if bias:
                out = out + bias[0]
            return out

        args = [x1, x2, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply("bilinear", fn, *args)
