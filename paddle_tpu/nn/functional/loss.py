"""Loss functionals.

Reference parity: python/paddle/nn/functional/loss.py. Cross-entropy follows
the reference's softmax_with_cross_entropy semantics (integer or soft labels,
ignore_index, label smoothing via label_smooth + soft labels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.registry import apply
from ...tensor_class import unwrap


def _reduce(loss, reduction, weight_sum=None):
    if reduction == "mean":
        if weight_sum is not None:
            return jnp.sum(loss) / jnp.maximum(weight_sum, 1e-12)
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    def fn(logits, lbl, *w):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.clip(logits, 1e-30, None))
        is_soft = soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape and jnp.issubdtype(lbl.dtype, jnp.inexact))
        safe_idx = None
        if is_soft:
            soft = lbl
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                soft = (1 - label_smoothing) * soft + label_smoothing / k
            loss = -jnp.sum(soft * logp, axis=axis)
            mask = None
        else:
            idx = lbl.astype(jnp.int32)
            if idx.ndim == logits.ndim:  # trailing [..., 1] label
                idx = jnp.squeeze(idx, axis=axis)
            mask = idx != ignore_index
            safe_idx = jnp.where(mask, idx, 0)
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                nll = -jnp.take_along_axis(logp, safe_idx[..., None], axis=axis)[..., 0]
                smooth = -jnp.mean(logp, axis=axis)
                loss = (1 - label_smoothing) * nll + label_smoothing * smooth
            else:
                loss = -jnp.take_along_axis(logp, safe_idx[..., None], axis=axis)[..., 0]
            loss = jnp.where(mask, loss, 0.0)
        wsum = None
        if w:
            cw = jnp.take(w[0], safe_idx if safe_idx is not None else jnp.argmax(lbl, axis=axis), axis=0)
            if mask is not None:
                cw = jnp.where(mask, cw, 0.0)
            loss = loss * cw
            wsum = jnp.sum(cw)
        elif mask is not None and reduction == "mean":
            wsum = jnp.sum(mask.astype(loss.dtype))
        return _reduce(loss, reduction, wsum)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply("cross_entropy", fn, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    from . import activation

    loss = loss.unsqueeze(axis) if loss.ndim < unwrap(logits).ndim else loss
    if return_softmax:
        return loss, activation.softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    """Input is already log-probabilities (paddle semantics): the loss is a
    plain negative gather, no log applied."""

    def fn(logp, lbl, *w):
        idx = lbl.astype(jnp.int32)
        mask = idx != ignore_index
        safe_idx = jnp.where(mask, idx, 0)
        loss = -jnp.take_along_axis(logp, safe_idx[..., None], axis=-1)[..., 0]
        loss = jnp.where(mask, loss, 0.0)
        wsum = None
        if w:
            cw = jnp.where(mask, jnp.take(w[0], safe_idx, axis=0), 0.0)
            loss = loss * cw
            wsum = jnp.sum(cw)
        elif reduction == "mean":
            wsum = jnp.sum(mask.astype(loss.dtype))
        return _reduce(loss, reduction, wsum)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply("nll_loss", fn, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, l, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(l * jnp.log(p) + (1 - l) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply("binary_cross_entropy", fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, l, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]
            i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable formulation
        neg_abs = -jnp.abs(z)
        if pw is not None:
            log_weight = 1 + (pw - 1) * l
            loss = (1 - l) * z + log_weight * (jnp.log1p(jnp.exp(neg_abs)) + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0.0) - z * l + jnp.log1p(jnp.exp(neg_abs))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = [logit, label] + [t for t in (weight, pos_weight) if t is not None]
    return apply("bce_with_logits", fn, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d / delta, jnp.abs(d) - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply("smooth_l1", fn, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply("huber", fn, input, label)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            loss = t * (jnp.log(jnp.clip(t, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply("kl_div", fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, l):
        return _reduce(jnp.maximum(0.0, -l * (a - b) + margin), reduction)

    return apply("margin_ranking", fn, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, l):
        loss = jnp.where(l == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return apply("hinge_embedding", fn, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, l):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(l == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply("cosine_embedding", fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + epsilon, p), axis=-1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + epsilon, p), axis=-1), 1 / p)
        if swap:
            dsn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + epsilon, p), axis=-1), 1 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply("triplet_margin", fn, input, positive, negative)


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, l):
        return -l * jnp.log(p + epsilon) - (1 - l) * jnp.log(1 - p + epsilon)

    return apply("log_loss", fn, input, label)


def square_error_cost(input, label):
    return apply("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, l, *nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * l + (1 - p) * (1 - l)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            alpha_t = alpha * l + (1 - alpha) * (1 - l)
            loss = alpha_t * loss
        if nrm:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)

    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply("sigmoid_focal", fn, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via jax: log_probs [T, B, C] (paddle layout)."""
    import optax

    def fn(lp, lbl, il, ll):
        # optax ctc expects [B, T, C] logits and padded labels
        logits = jnp.transpose(lp, (1, 0, 2))
        B, T, C = logits.shape
        logit_padding = (jnp.arange(T)[None, :] >= il[:, None]).astype(jnp.float32)
        label_padding = (jnp.arange(lbl.shape[1])[None, :] >= ll[:, None]).astype(jnp.float32)
        loss = optax.ctc_loss(logits, logit_padding, lbl.astype(jnp.int32), label_padding, blank_id=blank)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(ll.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply("ctc_loss", fn, log_probs, labels, input_lengths, label_lengths)
