"""paddle.nn.functional.flash_attention as a MODULE (reference layout:
python/paddle/nn/functional/flash_attention.py — users import the
functions from this path). The module is additionally callable, forwarding
to the flash_attention function, so code written against this build's
earlier function-valued ``F.flash_attention`` keeps working.
"""
from __future__ import annotations

import sys
import types

from .attention import (  # noqa: F401
    flash_attention, flash_attn_unpadded, scaled_dot_product_attention,
    sdp_kernel)
from .extra import (  # noqa: F401
    flash_attn_qkvpacked, flash_attn_varlen_qkvpacked, flashmask_attention)

__all__ = ["flash_attention", "flash_attn_unpadded", "flash_attn_qkvpacked",
           "flash_attn_varlen_qkvpacked", "flashmask_attention",
           "scaled_dot_product_attention", "sdp_kernel"]


class _CallableModule(types.ModuleType):
    def __call__(self, *args, **kwargs):
        return flash_attention(*args, **kwargs)


sys.modules[__name__].__class__ = _CallableModule
