"""paddle.nn.functional.flash_attention as a MODULE (reference layout:
python/paddle/nn/functional/flash_attention.py — users import the
functions from this path). The module is additionally callable, forwarding
to the flash_attention function, so code written against this build's
earlier function-valued ``F.flash_attention`` keeps working.
"""
from __future__ import annotations

import sys
import types

from .attention import (  # noqa: F401
    flash_attention, flash_attn_unpadded, scaled_dot_product_attention,
    sdp_kernel)
from .extra import (  # noqa: F401
    flash_attn_qkvpacked, flash_attn_varlen_qkvpacked, flashmask_attention)

__all__ = ["flash_attention", "flash_attn_unpadded", "flash_attn_qkvpacked",
           "flash_attn_varlen_qkvpacked", "flashmask_attention",
           "scaled_dot_product_attention", "sdp_kernel",
           "get_triangle_upper_mask", "calc_reduced_attention_scores"]


class _CallableModule(types.ModuleType):
    def __call__(self, *args, **kwargs):
        return flash_attention(*args, **kwargs)


sys.modules[__name__].__class__ = _CallableModule


def get_triangle_upper_mask(x, name=None):
    """flash_attention.py:63 parity: a -1e4 strictly-upper-triangular
    additive mask shaped like ``x`` (the [B, H, S, S] score layout)."""
    import jax.numpy as jnp

    from ...tensor_class import unwrap, wrap

    a = unwrap(x)
    mask = jnp.triu(jnp.full(a.shape, -1e4, a.dtype), k=1)
    return wrap(mask)  # wrap() defaults stop_gradient=True


def calc_reduced_attention_scores(query, key, softmax_lse, name=None):
    """flash_attention.py:1832 parity: reduce_sum over the QUERY axis of
    softmax(QK^T/sqrt(d)) using a PRECOMPUTED logsumexp (the flash
    kernel's saved statistic) — probs are rebuilt blocklessly but never
    normalized twice. query [B,Sq,H,D], key [B,Sk,H,D],
    softmax_lse [B,H,Sq] -> [B,H,1,Sk]."""
    import jax.numpy as jnp

    from ...tensor_class import unwrap, wrap

    qa = unwrap(query)
    q = qa.astype(jnp.float32)
    k = unwrap(key).astype(jnp.float32)
    lse = unwrap(softmax_lse).astype(jnp.float32)
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(d, jnp.float32))
    probs = jnp.exp(scores - lse[..., None])
    out = probs.sum(axis=-2, keepdims=True)          # reduce over queries
    return wrap(out.astype(qa.dtype))
