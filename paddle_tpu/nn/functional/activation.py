"""Activation functionals.

Reference parity: python/paddle/nn/functional/activation.py (+ the phi
activation kernels). All are single jnp expressions — XLA fuses them into
surrounding matmuls, which is the TPU replacement for the reference's fused
activation CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.registry import apply, defop
from ...framework import random as _random


@defop("relu")
def relu(x):
    return jax.nn.relu(x)


@defop("relu6")
def relu6(x):
    return jax.nn.relu6(x)


@defop("relu_")
def relu_(x):
    return jax.nn.relu(x)


def gelu(x, approximate=False, name=None):
    return apply("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


@defop("silu")
def silu(x):
    return jax.nn.silu(x)


swish = silu


@defop("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a: jax.nn.elu(a, alpha=alpha), x)


@defop("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a: jax.nn.celu(a, alpha=alpha), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope=negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        # per-channel weight
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply("prelu", fn, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    if training:
        key = _random.next_key()

        def fn(a):
            slope = jax.random.uniform(key, a.shape, dtype=a.dtype, minval=lower, maxval=upper)
            return jnp.where(a >= 0, a, slope * a)

        return apply("rrelu", fn, x)
    mid = (lower + upper) / 2.0
    return apply("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


@defop("hardswish")
def hardswish(x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), x)


@defop("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ...framework.dtype import convert_dtype

            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)

    return apply("log_softmax", fn, x)


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ...framework.dtype import convert_dtype

            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)

    return apply("softmax", fn, x)


softmax_ = softmax


def softplus(x, beta=1.0, threshold=20.0, name=None):
    def fn(a):
        scaled = beta * a
        return jnp.where(scaled > threshold, a, jax.nn.softplus(scaled) / beta)

    return apply("softplus", fn, x)


def softshrink(x, threshold=0.5, name=None):
    return apply(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)),
        x,
    )


@defop("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@defop("tanhshrink")
def tanhshrink(x):
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), x)


def glu(x, axis=-1, name=None):
    return apply("glu", lambda a: jax.nn.glu(a, axis=axis), x)


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return apply("maxout", fn, x)


def tanh(x, name=None):
    from ...ops import math as _math

    return _math.tanh(x)


def sigmoid(x, name=None):
    from ...ops import math as _math

    return _math.sigmoid(x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = _random.next_key()

    def fn(a):
        g = jax.random.gumbel(key, a.shape, dtype=a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y  # straight-through estimator
        return y

    return apply("gumbel_softmax", fn, x)
