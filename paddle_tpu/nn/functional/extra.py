"""F.* round-3 tail: distance/pad/pool/loss/attention functions closing the
nn.functional __all__ gap vs the reference
(python/paddle/nn/functional/__init__.py).

Each function cites its reference implementation; all are pure-jax through
``apply`` so AMP/NaN-check/tape integration comes from the registry.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.registry import apply, inplace_swap
from ...tensor_class import Tensor, unwrap, wrap


def _reduce(loss, reduction):
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


# ---------------------------------------------------------------------------
# distances / padding / misc
# ---------------------------------------------------------------------------

def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """F.pairwise_distance (python/paddle/nn/functional/distance.py)."""
    def fn(a, b):
        d = a - b + epsilon
        return jnp.power(jnp.power(jnp.abs(d), p).sum(-1, keepdims=keepdim),
                         1.0 / p)

    return apply("pairwise_distance", fn, x, y)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """F.zeropad2d (common.py zeropad2d): [left, right, top, bottom]."""
    l, r, tp, b = [int(unwrap(v)) for v in padding]
    def fn(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (tp, b), (l, r)))
        return jnp.pad(a, ((0, 0), (tp, b), (l, r), (0, 0)))

    return apply("zeropad2d", fn, x)


def bilinear(x1, x2, weight, bias=None, name=None):
    """F.bilinear (common.py bilinear): out[b,o] = x1[b,i] W[o,i,j] x2[b,j]."""
    def fn(a, b, w, *bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out

    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply("bilinear", fn, *args)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """F.feature_alpha_dropout: alpha dropout zeroing whole channels
    (dim 1), keeping self-normalizing statistics (SELU alpha dropout)."""
    if not training or p == 0.0:
        return x

    from ...framework import random as _random

    alpha = -1.7580993408473766
    key = _random.next_key()

    def fn(a):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1 - p, shape)
        kp = 1 - p
        # affine correction restoring N(0,1) stats: var of the dropped
        # mixture is kp*(1 + p*alpha^2), mean is p*alpha
        q = 1.0 / math.sqrt(kp * (1 + p * alpha * alpha))
        b = -q * alpha * p
        return (jnp.where(keep, a, alpha) * q + b).astype(a.dtype)

    return apply("feature_alpha_dropout", fn, x)


def gather_tree(ids, parents):
    """F.gather_tree (ops.yaml `gather_tree`): trace beam-search parent
    pointers backwards so each beam holds its full token path."""
    def fn(i, p):
        T = i.shape[0]

        def step(carry, xs):
            beams = carry  # [batch, beam] indices into next step
            tok, par = xs
            out = jnp.take_along_axis(tok, beams, axis=1)
            nxt = jnp.take_along_axis(par, beams, axis=1)
            return nxt, out

        init = jnp.broadcast_to(jnp.arange(i.shape[2]), i.shape[1:])
        _, rev = jax.lax.scan(step, init, (i[::-1], p[::-1]))
        return rev[::-1]

    return apply("gather_tree", fn, ids, parents, differentiable=False)


def class_center_sample(label, num_classes, num_samples, group=None):
    """F.class_center_sample (ops.yaml `class_center_sample`): sample the
    positive class centers plus negatives up to num_samples; labels are
    remapped into the sampled index space. Data-dependent sizes → eager
    host-side (the margin-softmax training loop calls it outside jit)."""
    lab = np.asarray(unwrap(label)).reshape(-1)
    pos = np.unique(lab)
    if pos.size >= num_samples:
        sampled = pos
    else:
        from ...framework.random import host_rng

        rest = np.setdiff1d(np.arange(num_classes), pos, assume_unique=True)
        rng = host_rng()  # framework key stream: fresh negatives per call,
        # reproducible under paddle.seed (reference draws fresh per call)
        extra = rng.choice(rest, size=num_samples - pos.size, replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones((num_classes,), np.int64)
    remap[sampled] = np.arange(sampled.size)
    return (wrap(jnp.asarray(remap[lab].astype(np.int64))),
            wrap(jnp.asarray(sampled.astype(np.int64))))


# ---------------------------------------------------------------------------
# in-place activations (reference exports *_ variants of these five)
# ---------------------------------------------------------------------------

def _inplace_of(fn_name):
    def op(x, *a, **k):
        from . import activation as _act

        out = getattr(_act, fn_name)(x, *a, **k)
        return inplace_swap(x, out)

    op.__name__ = fn_name + "_"
    return op


elu_ = _inplace_of("elu")
hardtanh_ = _inplace_of("hardtanh")
leaky_relu_ = _inplace_of("leaky_relu")
tanh_ = _inplace_of("tanh")
thresholded_relu_ = _inplace_of("thresholded_relu")


# ---------------------------------------------------------------------------
# pooling tail
# ---------------------------------------------------------------------------

def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """F.lp_pool1d via the 2-D kernel (width-1 axis)."""
    from . import lp_pool2d

    if data_format == "NLC":
        x = x.transpose([0, 2, 1])
    elif data_format != "NCL":
        raise ValueError(f"lp_pool1d: unknown data_format {data_format!r}")
    x4 = x.unsqueeze(-1) if isinstance(x, Tensor) else wrap(unwrap(x)[..., None])
    out = lp_pool2d(x4, norm_type, (kernel_size, 1),
                    (stride if stride is not None else kernel_size, 1),
                    (padding, 0), ceil_mode, "NCHW")
    out = out.squeeze(-1)
    return out.transpose([0, 2, 1]) if data_format == "NLC" else out


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    """F.max_unpool1d (ops.yaml `unpool`) via the 2-D kernel."""
    from . import max_unpool2d

    out_2d = None
    if output_size is not None:
        out_2d = list(output_size[:-1]) + [output_size[-1], 1] \
            if len(output_size) > 1 else [output_size[-1], 1]
    out = max_unpool2d(x.unsqueeze(-1), indices.unsqueeze(-1),
                       (kernel_size, 1),
                       (stride if stride is not None else kernel_size, 1),
                       (padding, 0), out_2d, "NCHW")
    return out.squeeze(-1)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    """F.max_unpool3d (ops.yaml `unpool3d`): scatter pooled values back to
    their argmax positions."""
    def trip(v):
        return (v, v, v) if isinstance(v, int) else tuple(v)

    kd, kh, kw = trip(kernel_size)
    sd, sh, sw = trip(stride if stride is not None else kernel_size)
    pd, ph, pw = trip(padding)

    def fn(a, idx):
        n, c, d, h, w = a.shape
        if output_size is None:
            od = (d - 1) * sd - 2 * pd + kd
            oh = (h - 1) * sh - 2 * ph + kh
            ow = (w - 1) * sw - 2 * pw + kw
        else:
            od, oh, ow = output_size[-3], output_size[-2], output_size[-1]
        flat = jnp.zeros((n, c, od * oh * ow), a.dtype)
        ii = idx.reshape(n, c, -1).astype(jnp.int32)
        out = flat.at[jnp.arange(n)[:, None, None],
                      jnp.arange(c)[None, :, None], ii].set(
            a.reshape(n, c, -1))
        return out.reshape(n, c, od, oh, ow)

    return apply("max_unpool3d", fn, x, indices)


def _fractional_windows(in_size, out_size, u, kernel):
    """Per-output (start, length) windows for fractional pooling (Graham
    2014). Disjoint partition mode when kernel is None (b_i..b_{i+1}); the
    overlapping kernel mode pools [b_i, b_i+k)."""
    alpha = in_size / out_size
    idx = np.arange(out_size + 1, dtype=np.float64)
    b = np.ceil(alpha * (idx + u)).astype(np.int64) - int(np.ceil(alpha * u))
    b = np.clip(b, 0, in_size)
    b[0] = 0
    b[-1] = in_size
    starts = b[:-1]
    if kernel is None:
        lens = np.maximum(b[1:] - b[:-1], 1)
    else:
        starts = np.minimum(starts, in_size - kernel)
        lens = np.full(out_size, kernel, np.int64)
    return starts, lens


def _fractional_pool_nd(x, out_sizes, u, kernels, return_mask):
    """Shared n-D fractional max pool: windows gathered on device (padded to
    the max window length with -inf, like _max_pool_with_mask), max+argmax
    in the same traced fn — no host recompute."""
    a_shape = unwrap(x).shape
    sp = a_shape[2:]
    nd = len(out_sizes)
    coords, valids = [], []
    for d in range(nd):
        starts, lens = _fractional_windows(sp[d], out_sizes[d], u,
                                           None if kernels is None
                                           else kernels[d])
        kmax = int(lens.max())
        c = starts[:, None] + np.arange(kmax)[None, :]
        v = np.arange(kmax)[None, :] < lens[:, None]
        v &= c < sp[d]
        coords.append(jnp.asarray(np.clip(c, 0, sp[d] - 1)))
        valids.append(jnp.asarray(v))

    def fn(arr):
        neg = jnp.asarray(-jnp.inf, jnp.float32)
        if nd == 2:
            win = arr[:, :, coords[0][:, None, :, None],
                      coords[1][None, :, None, :]]
            ok = (valids[0][:, None, :, None]
                  & valids[1][None, :, None, :])[None, None]
            lin = (coords[0][:, None, :, None] * sp[1]
                   + coords[1][None, :, None, :])
            lead = 4
        else:
            win = arr[:, :, coords[0][:, None, None, :, None, None],
                      coords[1][None, :, None, None, :, None],
                      coords[2][None, None, :, None, None, :]]
            ok = (valids[0][:, None, None, :, None, None]
                  & valids[1][None, :, None, None, :, None]
                  & valids[2][None, None, :, None, None, :])[None, None]
            lin = ((coords[0][:, None, None, :, None, None] * sp[1]
                    + coords[1][None, :, None, None, :, None]) * sp[2]
                   + coords[2][None, None, :, None, None, :])
            lead = 5
        win = jnp.where(ok, win.astype(jnp.float32), neg)
        wf = win.reshape(win.shape[:lead] + (-1,))
        mx = wf.max(-1).astype(arr.dtype)
        am = wf.argmax(-1)
        linb = jnp.broadcast_to(lin.reshape(lin.shape[:nd] + (-1,)), wf.shape)
        idx = jnp.take_along_axis(linb, am[..., None], -1)[..., 0]
        return mx, idx.astype(jnp.int64)

    mx, idx = apply("fractional_max_pool", fn, x)
    return (mx, idx) if return_mask else mx


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """F.fractional_max_pool2d (ops.yaml `fractional_max_pool2d`)."""
    out = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    ks = None if kernel_size is None else (
        (kernel_size, kernel_size) if isinstance(kernel_size, int)
        else tuple(kernel_size))
    u = float(random_u) if random_u is not None else 0.5
    return _fractional_pool_nd(x, out, u, ks, return_mask)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """F.fractional_max_pool3d (ops.yaml `fractional_max_pool3d`)."""
    out = (output_size,) * 3 if isinstance(output_size, int) \
        else tuple(output_size)
    ks = None if kernel_size is None else (
        (kernel_size,) * 3 if isinstance(kernel_size, int)
        else tuple(kernel_size))
    u = float(random_u) if random_u is not None else 0.5
    return _fractional_pool_nd(x, out, u, ks, return_mask)


# ---------------------------------------------------------------------------
# loss tail
# ---------------------------------------------------------------------------

def dice_loss(input, label, epsilon=1e-5, name=None):
    """F.dice_loss (loss.py dice_loss): 1 - 2|X∩Y| / (|X|+|Y|)."""
    def fn(p, l):
        l1 = jax.nn.one_hot(l.squeeze(-1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = (p * l1).sum(reduce_dims)
        union = p.sum(reduce_dims) + l1.sum(reduce_dims)
        return (1 - (2 * inter + epsilon) / (union + epsilon)).mean()

    return apply("dice_loss", fn, input, label)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    """F.poisson_nll_loss (loss.py poisson_nll_loss)."""
    def fn(x, t):
        if log_input:
            loss = jnp.exp(x) - t * x
        else:
            loss = x - t * jnp.log(x + epsilon)
        if full:
            stirling = t * jnp.log(t) - t + 0.5 * jnp.log(2 * jnp.pi * t)
            loss = loss + jnp.where(t > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply("poisson_nll_loss", fn, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """F.gaussian_nll_loss (loss.py gaussian_nll_loss)."""
    def fn(mu, t, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (t - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)

    return apply("gaussian_nll_loss", fn, input, label, variance)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    """F.triplet_margin_with_distance_loss (loss.py)."""
    dist = distance_function or (
        lambda a, b: pairwise_distance(a, b))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dpn = dist(positive, negative)
        dn = wrap(jnp.minimum(unwrap(dn), unwrap(dpn)))

    def fn(p, n):
        return _reduce(jnp.maximum(p - n + margin, 0.0), reduction)

    return apply("triplet_margin_with_distance_loss", fn, dp, dn)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """F.hsigmoid_loss (ops.yaml `hsigmoid_loss`): hierarchical sigmoid over
    the default complete binary tree (leaf l ↔ node num_classes + l;
    internal nodes 1..num_classes-1 carry rows of `weight`), or a custom
    (path_table, path_code) pair — the reference MatrixBitCode scheme."""
    depth = max(1, int(math.ceil(math.log2(max(num_classes, 2)))))

    if path_table is None:
        lab = np.asarray(unwrap(label)).reshape(-1).astype(np.int64)
        nodes = np.zeros((lab.size, depth), np.int64)
        codes = np.zeros((lab.size, depth), np.float32)
        valid = np.zeros((lab.size, depth), np.float32)
        for r, l in enumerate(lab):
            c = int(l) + num_classes
            k = 0
            path = []
            while c > 1:
                path.append((c >> 1, float(c & 1)))
                c >>= 1
            for k, (node, bit) in enumerate(reversed(path)):
                if k < depth:
                    nodes[r, k] = node - 1  # weight row for internal node
                    codes[r, k] = bit
                    valid[r, k] = 1.0
        tbl, code, msk = (jnp.asarray(nodes), jnp.asarray(codes),
                          jnp.asarray(valid))
    else:
        tbl = jnp.asarray(unwrap(path_table)).astype(jnp.int32)
        code = jnp.asarray(unwrap(path_code)).astype(jnp.float32)
        msk = (tbl >= 0).astype(jnp.float32)
        tbl = jnp.maximum(tbl, 0)

    def fn(x, w, *b):
        wv = w[tbl]                      # [batch, depth, feat]
        logits = jnp.einsum("bf,bdf->bd", x, wv)
        if b:
            logits = logits + b[0].reshape(-1)[tbl]
        # bit=1 → sigmoid(logit) target 1? The reference uses
        # sum over path of softplus((1-2*code)*logit)
        loss = jax.nn.softplus((1.0 - 2.0 * code) * logits) * msk
        return loss.sum(-1).mean()

    args = (input, weight) + ((bias,) if bias is not None else ())
    return apply("hsigmoid_loss", fn, *args)


def rnnt_loss(logits, labels, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """F.rnnt_loss (ops.yaml `warprnnt`): RNN-Transducer loss via the
    forward (alpha) recursion in log space — lax.scan over time frames, a
    sequential scan over label positions inside each frame."""
    def fn(lg, lb, il, ll):
        lp = jax.nn.log_softmax(lg, -1)           # [B, T, U1, V]
        B, T, U1, V = lp.shape
        blank_lp = lp[..., blank]                  # [B, T, U1]
        lab = lb.astype(jnp.int32)                 # [B, U]
        lab_lp = jnp.take_along_axis(
            lp[:, :, :-1, :], lab[:, None, :, None], -1)[..., 0]  # [B,T,U]
        if fastemit_lambda:
            # FastEmit (warprnnt semantics): the loss VALUE is the plain
            # transducer NLL; only label-emission gradients scale by
            # (1+λ). value(x)=x, grad(x)=(1+λ)·dx via the stop-grad split:
            lab_lp = ((1.0 + fastemit_lambda) * lab_lp
                      - jax.lax.stop_gradient(fastemit_lambda * lab_lp))
        neg = jnp.asarray(-1e30, lp.dtype)
        alpha0 = jnp.full((B, U1), neg).at[:, 0].set(0.0)

        def scan_t(alpha, t):
            blank_prev = blank_lp[:, t - 1, :]
            horiz = jnp.where(t == 0, alpha, alpha + blank_prev)

            def u_step(carry, ys):
                h, l = ys
                return jnp.logaddexp(h, carry + l), \
                    jnp.logaddexp(h, carry + l)

            first = horiz[:, 0]
            _, rest = jax.lax.scan(u_step, first,
                                   (horiz[:, 1:].T, lab_lp[:, t, :].T))
            out = jnp.concatenate([first[:, None], rest.T], 1)
            return out, out

        _, alphas = jax.lax.scan(scan_t, alpha0, jnp.arange(T))
        # total log prob: alpha[T-1, U] + blank at (T-1, U)
        tl = (il - 1).astype(jnp.int32)            # last frame index
        ul = ll.astype(jnp.int32)                  # last label index
        a_end = alphas[tl, jnp.arange(B), ul]
        final_blank = blank_lp[jnp.arange(B), tl, ul]
        nll = -(a_end + final_blank)
        return _reduce(nll, reduction)

    return apply("rnnt_loss", fn, logits, labels, input_lengths,
                 label_lengths)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """F.adaptive_log_softmax_with_loss (loss.py): adaptive softmax
    (Grave et al.): a head over [shortlist + clusters], low-rank tails per
    cluster. Returns (per-sample logprob, mean nll loss)."""
    cutoffs = [int(c) for c in cutoffs]
    shortlist = cutoffs[0]

    def fn(x, lbl, hw, *rest):
        has_bias = head_bias is not None
        hb = rest[0] if has_bias else None
        tails = rest[1:] if has_bias else rest
        head = x @ hw
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head, -1)     # [B, shortlist+K]
        lbl = lbl.astype(jnp.int32)
        out = jnp.zeros(lbl.shape, x.dtype)
        # shortlist words
        in_short = lbl < shortlist
        short_lp = jnp.take_along_axis(
            head_lp, jnp.minimum(lbl, shortlist - 1)[:, None], -1)[:, 0]
        out = jnp.where(in_short, short_lp, out)
        # clusters
        bounds = [shortlist] + cutoffs[1:] if len(cutoffs) > 1 else [shortlist]
        for k in range(len(tails) // 2):
            lo = bounds[k]
            hi = bounds[k + 1] if k + 1 < len(bounds) else lo
            proj, cls_w = tails[2 * k], tails[2 * k + 1]
            tail_logits = (x @ proj) @ cls_w
            tail_lp = jax.nn.log_softmax(tail_logits, -1)
            in_k = (lbl >= lo) & (lbl < hi)
            rel = jnp.clip(lbl - lo, 0, tail_lp.shape[-1] - 1)
            lp_k = head_lp[:, shortlist + k] + jnp.take_along_axis(
                tail_lp, rel[:, None], -1)[:, 0]
            out = jnp.where(in_k, lp_k, out)
        return out, -out.mean()

    args = [input, label, head_weight]
    if head_bias is not None:
        args.append(head_bias)
    for pair in tail_weights:
        args.extend(pair)
    return apply("adaptive_log_softmax_with_loss", fn, *args)


# ---------------------------------------------------------------------------
# attention tail
# ---------------------------------------------------------------------------

def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """F.sparse_attention (ops.yaml `sparse_attention`): attention evaluated
    only at a CSR-described sparsity pattern. TPU-native: dense QK^T on the
    MXU with an additive -inf mask built from the CSR structure (see
    sparse/nn.py rationale)."""
    def fn(q, k, v, offs, cols):
        B, H, S, D = q.shape
        scores = q @ jnp.swapaxes(k, -1, -2) / jnp.sqrt(float(D))

        def row_mask(offs_bh, cols_bh):
            # CSR → dense boolean mask: element j belongs to the row r with
            # offs[r] <= j < offs[r+1]
            m = jnp.zeros((S, S), bool)
            seg = jnp.searchsorted(offs_bh, jnp.arange(cols_bh.shape[0]),
                                   side="right") - 1
            return m.at[seg, cols_bh].set(True)

        mask = jax.vmap(jax.vmap(row_mask))(offs.astype(jnp.int32),
                                            cols.astype(jnp.int32))
        neg = jnp.asarray(-1e9, scores.dtype)
        scores = jnp.where(mask, scores, neg)
        return jax.nn.softmax(scores, -1) @ v

    return apply("sparse_attention", fn, query, key, value,
                 sparse_csr_offset, sparse_csr_columns)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, name=None):
    """F.flashmask_attention (incubate flashmask): column-sparse causal
    masking described by per-column start rows (and optional end rows).
    startend_row_indices [B, H or 1, S, 1|2|4]; None → plain (causal)
    attention via the flash path."""
    from .attention import scaled_dot_product_attention

    if startend_row_indices is None:
        return scaled_dot_product_attention(query, key, value,
                                            is_causal=causal)

    def fn(q, k, v, se):
        B, S, H, D = q.shape
        qh = jnp.moveaxis(q, 2, 1)
        kh = jnp.moveaxis(k, 2, 1)
        vh = jnp.moveaxis(v, 2, 1)
        scores = qh @ jnp.swapaxes(kh, -1, -2) / jnp.sqrt(float(D))
        rows = jnp.arange(S)[:, None]          # query index
        cols = jnp.arange(S)[None, :]          # key index
        se = se.astype(jnp.int32)              # [B, Hm, S, n]
        n = se.shape[-1]
        if causal:
            # per-key-column band: banned where start[col] <= row < end[col]
            end = se[..., 1] if n >= 2 else jnp.full_like(se[..., 0], S)
            st = se[..., 0][..., None, :]      # [B,Hm,1,S] broadcast over rows
            en = end[..., None, :]
            banned = (rows >= st) & (rows < en)
            allow = (rows >= cols) & ~banned
        else:
            # bidirectional: n==2 means [LTStart, UTEnd] (flashmask spec);
            # n==4 is the full [LTS, LTE, UTS, UTE]
            lts = se[..., 0][..., None, :]
            if n >= 4:
                lte = se[..., 1][..., None, :]
                uts = se[..., 2][..., None, :]
                ute = se[..., 3][..., None, :]
            else:
                lte = jnp.full_like(lts, S)
                uts = jnp.zeros_like(lts)
                ute = (se[..., 1] if n >= 2
                       else jnp.zeros_like(se[..., 0]))[..., None, :]
            banned_low = (rows >= lts) & (rows < lte)
            banned_up = (rows >= uts) & (rows < ute)
            allow = ~(banned_low | banned_up)
        neg = jnp.asarray(-1e9, scores.dtype)
        scores = jnp.where(allow, scores, neg)
        out = jax.nn.softmax(scores, -1) @ vh
        return jnp.moveaxis(out, 1, 2)

    return apply("flashmask_attention", fn, query, key, value,
                 startend_row_indices)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         name=None):
    """F.flash_attn_qkvpacked: packed [B, S, 3, H, D] → flash attention."""
    from .attention import flash_attention

    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    out, sm = flash_attention(q, k, v, dropout=dropout, causal=causal,
                              return_softmax=return_softmax)
    if return_softmax:
        return out, sm
    return out


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, name=None):
    """F.flash_attn_varlen_qkvpacked: ragged batch described by cumulative
    sequence lengths [total_tokens, 3, H, D]. Each segment runs through the
    flash path; segments are static python slices (host-side lengths —
    matching the reference's eager varlen API)."""
    from .attention import flash_attention

    cu = np.asarray(unwrap(cu_seqlens_q)).astype(np.int64)
    packed = unwrap(qkv)
    outs = []
    for i in range(cu.size - 1):
        seg = packed[cu[i]:cu[i + 1]]           # [s_i, 3, H, D]
        q, k, v = seg[:, 0], seg[:, 1], seg[:, 2]
        o, _ = flash_attention(wrap(q[None]), wrap(k[None]), wrap(v[None]),
                               dropout=dropout, causal=causal)
        outs.append(unwrap(o)[0])
    return wrap(jnp.concatenate(outs, 0))
