"""Convolution / pooling functionals over jax.lax.

Reference parity: python/paddle/nn/functional/{conv,pooling}.py (kernels:
paddle/phi/kernels/gpudnn/conv_kernel.cu etc.). Convs are MXU ops on TPU —
jax.lax.conv_general_dilated lowers to XLA convolution which maps directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.registry import apply
from ...tensor_class import unwrap


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _conv_padding(padding, n, kernel, dilation):
    """Normalise paddle padding spec → lax padding list of (lo, hi)."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _dimension_numbers(ndim, channel_last):
    if ndim == 3:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if ndim == 4:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format, transpose=False, output_padding=0):
    channel_last = data_format[-1] == "C"
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)

    def fn(a, w, *b):
        if transpose:
            # Transposed conv as a fractionally-strided conv: dilate the input
            # by `stride` (lhs_dilation) and run a unit-stride conv with the
            # spatially-flipped kernel. Paddle weight layout is
            # [in, out/groups, *k] → regroup to [out, in/groups, *k].
            cin = w.shape[0]
            w_g = w.reshape(groups, cin // groups, w.shape[1], *w.shape[2:])
            w_oi = jnp.swapaxes(w_g, 1, 2).reshape(groups * w.shape[1], cin // groups, *w.shape[2:])
            w_oi = jnp.flip(w_oi, axis=tuple(range(2, 2 + n)))
            pad = _conv_padding(padding, n, None, None)
            if isinstance(pad, str):
                raise ValueError("string padding unsupported for conv_transpose")
            opad = _tuple(output_padding, n)
            kshape = w.shape[2:]
            tpad = [
                (dil[i] * (kshape[i] - 1) - pad[i][0],
                 dil[i] * (kshape[i] - 1) - pad[i][1] + opad[i])
                for i in range(n)
            ]
            dn = jax.lax.conv_dimension_numbers(a.shape, w_oi.shape, _dimension_numbers(a.ndim, channel_last))
            out = jax.lax.conv_general_dilated(
                a, w_oi, (1,) * n, tpad, lhs_dilation=strides, rhs_dilation=dil,
                dimension_numbers=dn, feature_group_count=groups,
            )
        else:
            dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, _dimension_numbers(a.ndim, channel_last))
            pad = _conv_padding(padding, n, w.shape, dil)
            out = jax.lax.conv_general_dilated(
                a, w, strides, pad, rhs_dilation=dil, dimension_numbers=dn,
                feature_group_count=groups,
            )
        if b:
            shape = [1] * out.ndim
            ch_axis = out.ndim - 1 if channel_last else 1
            shape[ch_axis] = b[0].size
            out = out + b[0].reshape(shape)
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply("conv_transpose" if transpose else "conv", fn, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format, True, output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format, True, output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format, True, output_padding)


# ---- pooling -----------------------------------------------------------------

def _pool(x, kernel, stride, padding, n, data_format, reducer, init, ceil_mode=False,
          count_include_pad=True, divisor_override=None, average=False):
    channel_last = data_format[-1] == "C"
    ks = _tuple(kernel, n)
    st = _tuple(stride if stride is not None else kernel, n)

    def fn(a):
        if channel_last:
            window = (1, *ks, 1)
            strides = (1, *st, 1)
            sp_dims = list(range(1, 1 + n))
        else:
            window = (1, 1, *ks)
            strides = (1, 1, *st)
            sp_dims = list(range(2, 2 + n))
        pad = _conv_padding(padding, n, ks, None)
        if isinstance(pad, str):
            pad_cfg = pad
        else:
            pad_cfg = [(0, 0)] * a.ndim
            for d, p in zip(sp_dims, pad):
                pad_cfg[d] = p
        if average:
            summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad_cfg)
            if divisor_override:
                return summed / divisor_override
            if count_include_pad or (isinstance(pad_cfg, str) or all(p == (0, 0) for p in pad_cfg)):
                return summed / np.prod(ks)
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_cfg)
            return summed / counts
        return jax.lax.reduce_window(a, init, reducer, window, strides, pad_cfg)

    return apply("pool", fn, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "NCL", jax.lax.add, 0.0, ceil_mode,
                 count_include_pad=not exclusive, average=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.add, 0.0, ceil_mode,
                 count_include_pad=not exclusive, divisor_override=divisor_override, average=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.add, 0.0, ceil_mode,
                 count_include_pad=not exclusive, divisor_override=divisor_override, average=True)


def _max_pool_with_mask(x, kernel_size, stride, padding, n, ceil_mode=False):
    """Max pooling that also returns flat argmax indices (the unpool
    contract — ops.yaml `max_pool2d_with_index`). Windows are gathered
    explicitly (static shapes), max+argmax over the window axis."""
    if isinstance(padding, str):
        raise NotImplementedError(
            "max_pool return_mask: string padding modes are not supported "
            "(pass explicit ints so unpool indices stay well-defined)")
    ks = _tuple(kernel_size, n)
    st = _tuple(stride if stride is not None else kernel_size, n)
    pd = _tuple(padding, n)

    def fn(a):
        sp = a.shape[2:]
        neg = jnp.asarray(-jnp.inf, jnp.float32)
        # absolute input coordinates per (out position, window offset)
        coords = []
        valid = []
        outs = []
        for d in range(n):
            num = sp[d] + 2 * pd[d] - ks[d]
            o = (num + st[d] - 1) // st[d] + 1 if ceil_mode else num // st[d] + 1
            outs.append(o)
            c = (jnp.arange(o) * st[d] - pd[d])[:, None] + jnp.arange(ks[d])
            coords.append(jnp.clip(c, 0, sp[d] - 1))
            valid.append((c >= 0) & (c < sp[d]))
        if n == 1:
            win = a[:, :, coords[0]]                       # [N,C,O,K]
            ok = valid[0][None, None]
            flat_idx = coords[0]
            win = jnp.where(ok, win.astype(jnp.float32), neg)
            am = win.argmax(-1)
            mx = win.max(-1).astype(a.dtype)
            idx = jnp.take_along_axis(
                jnp.broadcast_to(flat_idx, win.shape), am[..., None], -1)[..., 0]
            return mx, idx.astype(jnp.int32)
        if n == 2:
            win = a[:, :, coords[0][:, None, :, None], coords[1][None, :, None, :]]
            ok = (valid[0][:, None, :, None] & valid[1][None, :, None, :])[None, None]
            lin = (coords[0][:, None, :, None] * sp[1]
                   + coords[1][None, :, None, :])          # [OH,OW,KH,KW]
            win = jnp.where(ok, win.astype(jnp.float32), neg)
            wf = win.reshape(win.shape[:4] + (-1,))
            am = wf.argmax(-1)
            mx = wf.max(-1).astype(a.dtype)
            linb = jnp.broadcast_to(lin.reshape(lin.shape[:2] + (-1,)), wf.shape)
            idx = jnp.take_along_axis(linb, am[..., None], -1)[..., 0]
            return mx, idx.astype(jnp.int32)
        # n == 3
        win = a[:, :, coords[0][:, None, None, :, None, None],
                coords[1][None, :, None, None, :, None],
                coords[2][None, None, :, None, None, :]]
        ok = (valid[0][:, None, None, :, None, None]
              & valid[1][None, :, None, None, :, None]
              & valid[2][None, None, :, None, None, :])[None, None]
        lin = ((coords[0][:, None, None, :, None, None] * sp[1]
                + coords[1][None, :, None, None, :, None]) * sp[2]
               + coords[2][None, None, :, None, None, :])
        win = jnp.where(ok, win.astype(jnp.float32), neg)
        wf = win.reshape(win.shape[:5] + (-1,))
        am = wf.argmax(-1)
        mx = wf.max(-1).astype(a.dtype)
        linb = jnp.broadcast_to(lin.reshape(lin.shape[:3] + (-1,)), wf.shape)
        idx = jnp.take_along_axis(linb, am[..., None], -1)[..., 0]
        return mx, idx.astype(jnp.int32)

    return apply("max_pool_with_index", fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1, ceil_mode)
    return _pool(x, kernel_size, stride, padding, 1, "NCL", jax.lax.max, -jnp.inf)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":
            raise NotImplementedError("max_pool2d return_mask: NCHW only")
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2, ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, data_format, jax.lax.max, -jnp.inf)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    if return_mask:
        if data_format != "NCDHW":
            raise NotImplementedError("max_pool3d return_mask: NCDHW only")
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3, ceil_mode)
    return _pool(x, kernel_size, stride, padding, 3, data_format, jax.lax.max, -jnp.inf)


def _adaptive_pool(x, output_size, n, data_format, average):
    channel_last = data_format[-1] == "C"
    out_sizes = _tuple(output_size, n)

    def fn(a):
        sp_dims = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out = a
        for d, o in zip(sp_dims, out_sizes):
            s = out.shape[d]
            if s % o == 0:
                k = s // o
                new_shape = out.shape[:d] + (o, k) + out.shape[d + 1:]
                r = out.reshape(new_shape)
                out = jnp.mean(r, axis=d + 1) if average else jnp.max(r, axis=d + 1)
            else:
                # general case: per-output-bin slices
                pieces = []
                for i in range(o):
                    lo = (i * s) // o
                    hi = -(-((i + 1) * s) // o)
                    sl = jax.lax.slice_in_dim(out, lo, hi, axis=d)
                    pieces.append(jnp.mean(sl, axis=d, keepdims=True) if average else jnp.max(sl, axis=d, keepdims=True))
                out = jnp.concatenate(pieces, axis=d)
        return out

    return apply("adaptive_avg_pool" if average else "adaptive_max_pool",
                 fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, True)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, True)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", False)
