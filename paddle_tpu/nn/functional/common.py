"""Common functionals: linear/embedding/dropout/normalization/padding/etc.

Reference parity: python/paddle/nn/functional/{common,input,norm}.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.registry import apply
from ...framework import random as _random
from ...framework.dtype import convert_dtype
from ...tensor_class import unwrap


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. Weight layout [in, out] (reference
    python/paddle/nn/functional/common.py::linear)."""
    if bias is None:
        return apply("linear", lambda a, w: a @ w, x, weight)
    return apply("linear", lambda a, w, b: a @ w + b, x, weight, bias)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # eager-mode bounds check (the reference's CPU/GPU lookup kernels
    # enforce this): jnp.take's out-of-range NaN fill would otherwise
    # poison the model silently. Concrete ids only; traced ids rely on
    # the model feeding valid data (XLA clamps).
    ids_arr = x._array if hasattr(x, "_array") else x
    try:
        vocab = (weight._array if hasattr(weight, "_array") else weight).shape[0]
        lo = int(jnp.min(ids_arr))
        hi = int(jnp.max(ids_arr))
        if lo < 0 or hi >= vocab:
            raise ValueError(
                f"embedding ids out of range: [{lo}, {hi}] vs vocab {vocab}")
    except jax.errors.TracerIntegerConversionError:
        pass
    except jax.errors.ConcretizationTypeError:  # pragma: no cover
        pass

    def fn(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros_like(out), out)
        return out

    return apply("embedding", fn, x, weight)


def one_hot(x, num_classes, name=None):
    return apply(
        "one_hot",
        lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes, dtype=jnp.float32),
        x,
        differentiable=False,
    )


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """Reference python/paddle/nn/functional/common.py::dropout semantics:
    upscale_in_train (inverted dropout, default) or downscale_in_infer."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply("dropout", lambda a: a * (1 - p), x)
        return x
    if p == 1.0:
        return apply("dropout", lambda a: jnp.zeros_like(a), x)
    key = _random.next_key()

    def fn(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros_like(a))
        return jnp.where(keep, a, jnp.zeros_like(a))

    return apply("dropout", fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _random.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        a_coef = (1.0 - p + p * alpha_p**2 * (1.0 - p)) ** -0.5
        b_coef = -a_coef * p * alpha_p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply("alpha_dropout", fn, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p)
        return a / jnp.maximum(nrm, epsilon)

    return apply("normalize", fn, x)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    ndims = len(normalized_shape)

    def fn(a, *wb):
        axes = tuple(range(a.ndim - ndims, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [t for t in (weight, bias) if t is not None]
    return apply("layer_norm", fn, x, *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm — reference fused kernel paddle/phi/kernels/fusion/gpu/rms_norm*;
    here a pure-XLA version (the Pallas fused variant lives in ops/pallas)."""

    def fn(a, *w):
        a32 = a.astype(jnp.float32)
        out = a32 * jax.lax.rsqrt(jnp.mean(jnp.square(a32), axis=-1, keepdims=True) + epsilon)
        out = out.astype(a.dtype)
        if w:
            out = out * w[0]
        return out

    args = [weight] if weight is not None else []
    return apply("rms_norm", fn, x, *args)


def batch_norm(
    x, running_mean, running_var, weight=None, bias=None, training=False,
    momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None,
):
    ch_axis = 1 if data_format[1] == "C" else -1

    if training and not use_global_stats:
        # Batch stats are computed INSIDE the recorded op so the full BN VJP
        # (including d mean/d x and d var/d x) flows through the tape.
        def fn(a, *wb):
            axes = tuple(i for i in range(a.ndim) if i != ch_axis % a.ndim)
            a32 = a.astype(jnp.float32)
            mean = jnp.mean(a32, axis=axes, keepdims=True)
            var = jnp.var(a32, axis=axes, keepdims=True)
            out = (a32 - mean) * jax.lax.rsqrt(var + epsilon)
            out = out.astype(a.dtype)
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out

        args = [t for t in (weight, bias) if t is not None]
        out = apply("batch_norm", fn, x, *args)

        # Running-stat update: eager only (under a jit trace this would leak
        # tracers into the buffers; compiled training uses functional state
        # or use_global_stats, as in other XLA frameworks).
        if running_mean is not None:
            from ...jit import is_tracing

            if not is_tracing():
                arr = unwrap(x)
                axes = tuple(i for i in range(arr.ndim) if i != ch_axis % arr.ndim)
                batch_mean = jnp.mean(arr.astype(jnp.float32), axis=axes)
                batch_var = jnp.var(arr.astype(jnp.float32), axis=axes)
                running_mean._array = (momentum * running_mean._array + (1 - momentum) * batch_mean).astype(running_mean.dtype)
                running_var._array = (momentum * running_var._array + (1 - momentum) * batch_var).astype(running_var.dtype)
        return out

    def fn(a, m, v, *wb):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a.astype(jnp.float32) - m.reshape(shape).astype(jnp.float32)) * jax.lax.rsqrt(
            v.reshape(shape).astype(jnp.float32) + epsilon
        )
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [t for t in (weight, bias) if t is not None]
    return apply("batch_norm", fn, x, running_mean, running_var, *args)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW", name=None):
    def fn(a, *wb):
        if data_format[1] != "C":
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        spatial = a_t.shape[2:]
        g = a_t.reshape(n, num_groups, c // num_groups, *spatial).astype(jnp.float32)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a_t.shape).astype(a.dtype)
        shape = [1, c] + [1] * len(spatial)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if data_format[1] != "C":
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [t for t in (weight, bias) if t is not None]
    return apply("group_norm", fn, x, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def fn(a, *wb):
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        axes = tuple(i for i in range(a.ndim) if i not in (0, ch_axis))
        a32 = a.astype(jnp.float32)
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        out = ((a32 - mean) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [t for t in (weight, bias) if t is not None]
    return apply("instance_norm", fn, x, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(a):
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[ch_axis]
        acc = jnp.zeros_like(sq)
        for offset in range(-half, half + (size % 2)):
            shifted = jnp.roll(sq, offset, axis=ch_axis)
            idx = jnp.arange(c)
            valid = (idx - offset >= 0) & (idx - offset < c)
            shape = [1] * a.ndim
            shape[ch_axis] = c
            acc = acc + jnp.where(valid.reshape(shape), shifted, 0.0)
        return a / jnp.power(k + alpha * acc, beta)

    return apply("local_response_norm", fn, x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k

    args = [prior_dist] if prior_dist is not None else []
    return apply("label_smooth", fn, label, *args)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        n1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        n2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(n1 * n2, eps)

    return apply("cosine_similarity", fn, x1, x2)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, c // (r * r), r, r, h, w)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, r, r, c // (r * r))
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h * r, w * r, c // (r * r))

    return apply("pixel_shuffle", fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, c, h // r, r, w // r, r)
            out = out.transpose(0, 1, 3, 5, 2, 4)
            return out.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        out = a.reshape(n, h // r, r, w // r, r, c)
        out = out.transpose(0, 2, 4, 1, 3, 5).reshape(n, h // r, w // r, c * r * r)
        return out

    return apply("pixel_unshuffle", fn, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (NCHW). Reference phi unfold kernel."""

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else (None, None)
    dh, dw = _pair(dilations)

    def fn(a):
        n, c, h, w = a.shape
        if ph is not None:
            ap = jnp.pad(a, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        else:
            p = paddings
            ap = jnp.pad(a, ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])))
        hp, wp = ap.shape[2], ap.shape[3]
        out_h = (hp - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (wp - (dw * (kw - 1) + 1)) // sw + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                sl = ap[:, :, i * dh : i * dh + out_h * sh : sh, j * dw : j * dw + out_w * sw : sw]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
        return out.reshape(n, c * kh * kw, out_h * out_w)

    return apply("unfold", fn, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    def fn(a):
        channel_last = data_format[-1] == "C"
        spatial_ndim = a.ndim - 2
        if channel_last:
            spatial = a.shape[1:-1]
        else:
            spatial = a.shape[2:]
        if size is not None:
            tgt = [int(unwrap(s)) for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_ndim
            tgt = [int(s * f) for s, f in zip(spatial, sf)]
        jax_mode = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear",
                    "linear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if channel_last:
            new_shape = (a.shape[0], *tgt, a.shape[-1])
        else:
            new_shape = (a.shape[0], a.shape[1], *tgt)
        return jax.image.resize(a, new_shape, method=jax_mode)

    return apply("interpolate", fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    def fn(l):
        m = maxlen or int(jnp.max(l))
        return (jnp.arange(m)[None, :] < l[..., None]).astype(convert_dtype(dtype))

    return apply("sequence_mask", fn, lengths, differentiable=False)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold : 2 * fold]), v[:, :-1, fold : 2 * fold]], axis=1)
        rest = v[:, :, 2 * fold :]
        out = jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply("temporal_shift", fn, x)
