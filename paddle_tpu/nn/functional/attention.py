"""Attention functionals: SDPA + flash attention.

Reference parity: python/paddle/nn/functional/flash_attention.py:195
(wrapping paddle/phi/kernels/gpu/flash_attn_kernel.cu) and
scaled_dot_product_attention. TPU-native: the fused path is a Pallas flash
kernel (ops/pallas/flash_attention.py); the fallback is pure-XLA SDPA which
XLA fuses reasonably. Layout follows paddle flash_attention: [batch, seqlen,
num_heads, head_dim].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.registry import apply
from ...framework import random as _random


def _sdpa_ref(q, k, v, mask=None, dropout=0.0, causal=False, scale=None,
              dropout_key=None, softcap=None):
    """Pure-XLA SDPA on [B, S, H, D] layout, f32 softmax accumulation.
    ``softcap``: Gemma2 tanh soft cap — scores become
    softcap * tanh(scores / softcap) before masking."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    # [B,H,Sq,Sk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * s
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """paddle.nn.functional.scaled_dot_product_attention parity.
    Layout [batch, seq, heads, head_dim]."""
    dk = _random.next_key() if (dropout_p > 0.0 and training) else None

    def fn(q, k, v, *m):
        mask = m[0] if m else None
        return _sdpa_ref(q, k, v, mask=mask, dropout=dropout_p if training else 0.0,
                         causal=is_causal, dropout_key=dk)

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    return apply("sdpa", fn, *args)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity
    (python/paddle/nn/functional/flash_attention.py:195).

    Dispatches to the Pallas TPU flash kernel when running on TPU with
    supported shapes; otherwise the XLA SDPA reference. Returns
    (out, softmax_lse-like None) tuple to match the reference's (out, softmax)
    when return_softmax=False.
    """
    from ...ops.pallas import flash_attention as pallas_flash

    dk = _random.next_key() if (dropout > 0.0 and training) else None

    def fn(q, k, v):
        if pallas_flash.supported(q, k, v, dropout):
            return pallas_flash.flash_attention_bshd(q, k, v, causal=causal)
        if k.shape[2] != q.shape[2]:
            # GQA inputs reaching the XLA fallback: expand KV heads (the
            # splash path handles grouping in-kernel; einsum cannot)
            from ...distributed.context_parallel import _expand_gqa

            k, v = _expand_gqa(k, v, q.shape[2])
        return _sdpa_ref(q, k, v, dropout=dropout if training else 0.0, causal=causal, dropout_key=dk)

    out = apply("flash_attention", fn, query, key, value)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    """paddle.nn.functional.flash_attention.flash_attn_unpadded parity:
    ragged batch of [total_tokens, H, D] with cumulative sequence lengths.
    Each segment runs through the flash path (host-side static lengths,
    like the reference's eager varlen API)."""
    import numpy as np

    from ...tensor_class import unwrap, wrap

    cq = np.asarray(unwrap(cu_seqlens_q)).astype(np.int64)
    ck = np.asarray(unwrap(cu_seqlens_k)).astype(np.int64)
    q, k, v = unwrap(query), unwrap(key), unwrap(value)
    if scale is not None:
        d = q.shape[-1]
        q = q * (scale * (d ** 0.5))  # fold custom scale over flash's 1/sqrt(d)
    outs = []
    for i in range(cq.size - 1):
        qs = q[cq[i]:cq[i + 1]][None]      # [1, s_q, H, D]
        ks = k[ck[i]:ck[i + 1]][None]
        vs = v[ck[i]:ck[i + 1]][None]
        o, _ = flash_attention(wrap(qs), wrap(ks), wrap(vs),
                               dropout=dropout, causal=causal)
        outs.append(unwrap(o)[0])
    out = wrap(jnp.concatenate(outs, 0))
    if return_softmax:
        return out, None
    return out


def sdp_kernel(*args, **kwargs):  # config context stub (torch-compat in ref)
    import contextlib

    return contextlib.nullcontext()
