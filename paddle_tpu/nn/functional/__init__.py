"""paddle_tpu.nn.functional — the F.* surface.

Reference parity: python/paddle/nn/functional/__init__.py.
"""
from .activation import *  # noqa: F401,F403
from .common import (  # noqa: F401
    linear, embedding, one_hot, dropout, dropout2d, dropout3d, alpha_dropout,
    normalize, layer_norm, rms_norm, batch_norm, group_norm, instance_norm,
    local_response_norm, label_smooth, cosine_similarity, pixel_shuffle,
    pixel_unshuffle, unfold, interpolate, upsample, sequence_mask,
    temporal_shift,
)
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose, avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d,
    max_pool2d, max_pool3d, adaptive_avg_pool1d, adaptive_avg_pool2d,
    adaptive_avg_pool3d, adaptive_max_pool1d, adaptive_max_pool2d,
    adaptive_max_pool3d,
)
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, binary_cross_entropy,
    binary_cross_entropy_with_logits, mse_loss, l1_loss, smooth_l1_loss,
    huber_loss, kl_div, margin_ranking_loss, hinge_embedding_loss,
    cosine_embedding_loss, triplet_margin_loss, log_loss, square_error_cost,
    sigmoid_focal_loss, ctc_loss,
)
from .attention import (  # noqa: F401
    scaled_dot_product_attention,
)
# flash_attention is a MODULE in the reference layout (and callable here
# for backward compatibility) — import last so the module wins the name
from . import flash_attention  # noqa: F401
from ...ops.manipulation import pad  # noqa: F401  (F.pad parity)
from ...ops import schema as _schema  # noqa: E402

# schema-generated tail (declared once in ops/schema.py — ops.yaml analog)
channel_shuffle = _schema.generated("channel_shuffle")
affine_grid = _schema.generated("affine_grid")
grid_sample = _schema.generated("grid_sample")
fold = _schema.generated("fold")
lp_pool2d = _schema.generated("lp_pool2d")
max_unpool2d = _schema.generated("max_unpool2d")
soft_margin_loss = _schema.generated("soft_margin_loss")
multi_margin_loss = _schema.generated("multi_margin_loss")
multi_label_soft_margin_loss = _schema.generated("multi_label_soft_margin_loss")
npair_loss = _schema.generated("npair_loss")
margin_cross_entropy = _schema.generated("margin_cross_entropy")
from .extra import (  # noqa: F401
    pairwise_distance, zeropad2d, bilinear, feature_alpha_dropout,
    gather_tree, class_center_sample, elu_, hardtanh_, leaky_relu_, tanh_,
    thresholded_relu_, lp_pool1d, max_unpool1d, max_unpool3d,
    fractional_max_pool2d, fractional_max_pool3d, dice_loss,
    poisson_nll_loss, gaussian_nll_loss, triplet_margin_with_distance_loss,
    hsigmoid_loss, rnnt_loss, adaptive_log_softmax_with_loss,
    sparse_attention, flashmask_attention, flash_attn_qkvpacked,
    flash_attn_varlen_qkvpacked,
)
