"""Recurrent layers over lax.scan.

Reference parity: python/paddle/nn/layer/rnn.py (SimpleRNN/LSTM/GRU + cells).
TPU-native: the time loop is ``lax.scan`` inside the recorded op — one XLA
while-loop, not a Python loop — so the whole RNN jits and differentiates as a
single computation. Weight layout matches the reference cells
(weight_ih [hidden*gates, input], weight_hh [hidden*gates, hidden]).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .layer import Layer
from .container import LayerList
from .initializer_core import Uniform
from ..ops.registry import apply
from ..tensor_class import unwrap


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        from ..ops import creation

        b = unwrap(batch_ref).shape[batch_dim_idx]
        return creation.full([b, self.hidden_size], init_value, dtype or "float32")


def _cell_params(layer, input_size, hidden_size, gates):
    std = 1.0 / math.sqrt(hidden_size)
    u = Uniform(-std, std)
    layer.weight_ih = layer.create_parameter([gates * hidden_size, input_size], default_initializer=u)
    layer.weight_hh = layer.create_parameter([gates * hidden_size, hidden_size], default_initializer=u)
    layer.bias_ih = layer.create_parameter([gates * hidden_size], is_bias=True, default_initializer=u)
    layer.bias_hh = layer.create_parameter([gates * hidden_size], is_bias=True, default_initializer=u)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1)

    def forward(self, inputs, states=None):
        states = states if states is not None else self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply("rnn_cell", fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 4)

    def forward(self, inputs, states=None):
        if states is None:
            h0 = self.get_initial_states(inputs)
            states = (h0, h0)
        h_prev, c_prev = states

        def fn(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new

        h, c = apply("lstm_cell", fn, inputs, h_prev, c_prev, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, (h, c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 3)

    def forward(self, inputs, states=None):
        h_prev = states if states is not None else self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        h = apply("gru_cell", fn, inputs, h_prev, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh)
        return h, h


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) RNN: lax.scan over time."""

    GATES = {"SimpleRNN": 1, "LSTM": 4, "GRU": 3}

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__()
        self.mode = mode
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        self.activation = activation
        gates = self.GATES[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for direction_i in range(self.num_directions):
                in_size = input_size if layer == 0 else hidden_size * self.num_directions
                suffix = "_reverse" if direction_i else ""
                wi = self.create_parameter([gates * hidden_size, in_size], default_initializer=u)
                wh = self.create_parameter([gates * hidden_size, hidden_size], default_initializer=u)
                bi = self.create_parameter([gates * hidden_size], is_bias=True, default_initializer=u)
                bh = self.create_parameter([gates * hidden_size], is_bias=True, default_initializer=u)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", wi)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", wh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", bi)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", bh)

    def _step(self, mode, activation):
        if mode == "LSTM":
            def step(carry, x, wi, wh, bi, bh):
                h, c = carry
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
                h = jax.nn.sigmoid(o) * jnp.tanh(c)
                return (h, c), h
        elif mode == "GRU":
            def step(carry, x, wi, wh, bi, bh):
                h = carry[0]
                gi = x @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                c = jnp.tanh(ic + r * hc)
                h = (1 - z) * c + z * h
                return (h,), h
        else:
            act = jnp.tanh if activation == "tanh" else jax.nn.relu

            def step(carry, x, wi, wh, bi, bh):
                h = act(x @ wi.T + bi + carry[0] @ wh.T + bh)
                return (h,), h

        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.mode
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        step = self._step(mode, self.activation)
        weights = []
        for layer in range(nl):
            for d in range(nd):
                suffix = "_reverse" if d else ""
                weights += [
                    getattr(self, f"weight_ih_l{layer}{suffix}"),
                    getattr(self, f"weight_hh_l{layer}{suffix}"),
                    getattr(self, f"bias_ih_l{layer}{suffix}"),
                    getattr(self, f"bias_hh_l{layer}{suffix}"),
                ]

        is_lstm = mode == "LSTM"

        # initial states: paddle layout [num_layers*num_directions, B, hidden]
        def fn(x, *flat):
            if initial_states is not None:
                if is_lstm:
                    h_init, c_init, flat_w = flat[0], flat[1], flat[2:]
                else:
                    h_init, c_init, flat_w = flat[0], None, flat[1:]
            else:
                h_init = c_init = None
                flat_w = flat
            xt = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, F]
            b = xt.shape[1]
            out = xt
            last_h, last_c = [], []
            wi_idx = 0
            for layer in range(nl):
                outs_dir = []
                for d in range(nd):
                    wi, wh, bi, bh = flat_w[wi_idx : wi_idx + 4]
                    wi_idx += 4
                    slot = layer * nd + d
                    if h_init is not None:
                        h0 = h_init[slot].astype(x.dtype)
                        c0 = c_init[slot].astype(x.dtype) if c_init is not None else h0
                    else:
                        h0 = jnp.zeros((b, hs), dtype=x.dtype)
                        c0 = h0
                    carry0 = (h0, c0) if is_lstm else (h0,)
                    seq = out if d == 0 else jnp.flip(out, axis=0)

                    def scan_fn(carry, xx, wi=wi, wh=wh, bi=bi, bh=bh):
                        return step(carry, xx, wi, wh, bi, bh)

                    carry, ys = jax.lax.scan(scan_fn, carry0, seq)
                    if d == 1:
                        ys = jnp.flip(ys, axis=0)
                    outs_dir.append(ys)
                    last_h.append(carry[0])
                    if is_lstm:
                        last_c.append(carry[1])
                out = jnp.concatenate(outs_dir, axis=-1) if nd == 2 else outs_dir[0]
            final = out if time_major else jnp.swapaxes(out, 0, 1)
            h_stack = jnp.stack(last_h, axis=0)
            if is_lstm:
                return final, h_stack, jnp.stack(last_c, axis=0)
            return final, h_stack

        extra = []
        if initial_states is not None:
            extra = [initial_states[0], initial_states[1]] if is_lstm else [initial_states]
        result = apply("rnn", fn, inputs, *extra, *weights)
        if is_lstm:
            out, h, c = result
            return out, (h, c)
        out, h = result
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("SimpleRNN", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)
