"""paddle.nn.quant parity (python/paddle/nn/quant/): weight-only
quantization ops + the quantized linear path used by LLM serving.

TPU-native: int8 weight-only quantize/dequantize are plain jnp (absmax
per-channel); weight_only_linear dequantizes into the matmul so XLA fuses
the scale into the MXU epilogue.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.registry import apply
from ...tensor_class import unwrap, wrap

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """ops.yaml `weight_quantize`: per-output-channel absmax int8.
    Returns (quantized int8 weight [in, out], scales [out])."""
    if algo not in ("weight_only_int8", "llm.int8"):
        raise NotImplementedError(f"weight_quantize: algo {algo!r} "
                                  "(int8 weight-only on TPU)")

    def fn(w):
        absmax = jnp.max(jnp.abs(w), axis=0)
        scale = jnp.maximum(absmax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    return apply("weight_quantize", fn, x, differentiable=False)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    from ...framework.dtype import convert_dtype

    dt = convert_dtype(out_dtype)

    def fn(q, s):
        return (q.astype(jnp.float32) * s).astype(dt)

    return apply("weight_dequantize", fn, x, scale, differentiable=False)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """ops.yaml `weight_only_linear`: y = x @ dequant(W) + b, scale fused
    by XLA into the matmul epilogue."""
    def fn(a, q, *rest):
        i = 0
        b = None
        s = None
        if bias is not None:
            b = rest[i]
            i += 1
        if weight_scale is not None:
            s = rest[i]
        w = q.astype(a.dtype)
        if s is not None:
            w = w * s.astype(a.dtype)
        out = a @ w
        if b is not None:
            out = out + b
        return out

    args = [x, weight]
    if bias is not None:
        args.append(bias)
    if weight_scale is not None:
        args.append(weight_scale)
    return apply("weight_only_linear", fn, *args)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """ops.yaml `llm_int8_linear`: LLM.int8() mixed decomposition —
    columns of x with outliers (|x| > threshold) run in the activation
    dtype against the dequantized weight, the rest in int8."""
    def fn(a, q, *rest):
        i = 0
        b = None
        s = None
        if bias is not None:
            b = rest[i]
            i += 1
        if weight_scale is not None:
            s = rest[i]
        # mixed decomposition (LLM.int8): regular columns run as a true
        # int8×int8→int32 matmul with per-row activation scales; outlier
        # feature columns (|x| > threshold anywhere) run in the activation
        # dtype against the dequantized weight
        outlier = (jnp.abs(a) > threshold).any(
            tuple(range(a.ndim - 1)))         # [in]
        a_reg = jnp.where(outlier, 0.0, a)
        a_absmax = jnp.max(jnp.abs(a_reg), axis=-1, keepdims=True)
        a_scale = jnp.maximum(a_absmax, 1e-8) / 127.0
        a_q = jnp.clip(jnp.round(a_reg / a_scale), -127, 127).astype(jnp.int8)
        int_out = jax.lax.dot_general(
            a_q, q, (((a_q.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        reg_out = int_out * a_scale
        if s is not None:
            reg_out = reg_out * s
        w_fp = q.astype(jnp.float32) * (s if s is not None else 1.0)
        a_out = jnp.where(outlier, a, 0.0)
        out = (reg_out + a_out.astype(jnp.float32) @ w_fp).astype(a.dtype)
        if b is not None:
            out = out + b
        return out

    args = [x, weight]
    if bias is not None:
        args.append(bias)
    if weight_scale is not None:
        args.append(weight_scale)
    return apply("llm_int8_linear", fn, *args)
